"""Continuous-batching decode engine: slot-admission rollout generation.

The fixed-batch sampler (``ops/sampling.py``) decodes B prompts in
lockstep: a row that emits eos at step 3 still occupies its batch lane
for all ``max_new_tokens`` steps, emitting pad — at the bench shape that
is the dominant collect-phase waste (BENCH_r05: collect MFU 0.157 vs
0.299 train). This engine replaces the lockstep with a **fixed pool of B
decode slots** and a host-side admission queue:

- ``decode_step`` advances every slot one token (one compiled program,
  static shapes — the pool IS the batch);
- the step after a row emits eos (or exhausts its budget) the host sees
  its ``done`` flag, harvests the finished rollout in a fixed-width
  group, and **prefills a fresh prompt into the vacated slot** — decode
  lanes never idle while prompts remain;
- per-row RNG keys (``fold_in(phase_key, row_draw_index)`` then
  ``fold_in(row_key, t)`` per step — ``ops/sampling.py::make_row_keys``/
  ``choose_tokens``) make each row's tokens independent of admission
  order and batch composition, so the engine is per-row token-identical
  to the fixed sampler under ``per_row_rng`` (the parity contract,
  tests/test_inference_engine.py);
- the KV cache is the paged/block cache (``inference/kv_cache.py``):
  slot recycling hands the new occupant a rotated block table, writes
  and reads resolve through the table, and ``kv_cache_dtype: int8`` and
  the sp-sharded capacity layout compose unchanged.

Three jitted programs per engine (registered with the analysis harness
as ``ppo.engine_prefill`` / ``ppo.engine_decode_step`` /
``ppo.engine_refill``):

- ``prefill(params, state, slots, prompts, mask, rows, turns, key)`` —
  admission: forward the padded prompt batch, write its KV through the
  (freshly rotated) block tables, seed per-slot sampling state;
- ``decode_step(params, state)`` — one token for every slot; emissions
  land in per-slot device output buffers; returns the [B] ``done``
  flags the host polls;
- ``refill(state, slots)`` — harvest: gather the finished slots'
  rollouts and mark the slots free (the admission queue refills them on
  the next poll).

Host loop cost model: one small [B]-bool device->host fetch per
``done_poll_interval`` decode steps (the admission decision needs the
flags; they are *sticky* — a finished slot stays done until harvested —
so polling only the latest step's flags every k-th step is exact). The
fetch is started asynchronously right behind each dispatch; at k=1 the
loop is bitwise-identical to polling every step (the parity contract,
tests/test_async_rl.py), at k>1 the fetch round-trip amortizes over k
dispatches and slots idle at most k-1 extra steps before harvest (the
group composition may then differ — per-row tokens never do).

Asynchronous actor–learner support (``train.async_rl``,
docs/async_pipeline.md): :meth:`push_weights` hands the engine a
refreshed behavior policy **mid-generation** — the swap is deferred to
the drive loop's safe point (after harvest bookkeeping, before the next
admission) so a push landing between a harvest and its refill can never
drop the queued admit group; rows are tagged with the params version
they were admitted under. :meth:`min_inflight_version` over those tags
is what the learner's bounded-staleness guard checks before each
update, and every harvest group carries the tags out to the stream
store's version column, where the learner reads them back as the
``async/consumed_lag`` attribution (how many updates old each consumed
minibatch's data is).
"""

from __future__ import annotations

import dataclasses
import inspect
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import flax.struct as struct
import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu import telemetry
from trlx_tpu.inference.kv_cache import choose_block_size
from trlx_tpu.ops.sampling import (
    GenerationConfig,
    accept_drafts,
    choose_tokens,
    concat_cols,
    make_row_keys,
)
from trlx_tpu.utils import sched_points


@struct.dataclass
class EngineState:
    """Device-resident state of the slot pool; every leaf's leading axis
    is the slot axis (sharded over dp×fsdp like any batch)."""

    cache: Any  # paged KV cache (tuple of per-layer dicts)
    row_keys: jax.Array  # [B, 2] uint32 per-row base keys
    t: jax.Array  # [B] int32 tokens emitted by the current occupant
    n_real: jax.Array  # [B] int32 real prompt length
    logits_last: jax.Array  # [B, V] float32 logits at the next decision
    value_last: jax.Array  # [B] float32 value estimate at that decision
    active: jax.Array  # [B] bool — slot holds an unharvested row
    finished: jax.Array  # [B] bool — row hit eos / length cap
    out_tokens: jax.Array  # [B, R] int32 (pad after eos)
    out_mask: jax.Array  # [B, R] int32
    out_logprobs: jax.Array  # [B, R] float32
    out_values: jax.Array  # [B, R] float32
    query_ids: jax.Array  # [B, Q] int32 (left-padded prompt)
    query_mask: jax.Array  # [B, Q] int32
    row_index: jax.Array  # [B] int32 global draw index of the occupant


@dataclasses.dataclass
class EngineStats:
    """Host-side occupancy/throughput counters for one phase.

    Single-thread contract (engine 14 allowlist): every counter is
    mutated only by the thread running the drive/pump loop; the metrics
    absorber and phase summaries read them at phase boundaries, after
    drive() returned on that same thread. No lock — cross-thread traffic
    into the engine goes through push_weights (the one locked entry)."""

    admitted: int = 0
    completed: int = 0
    prefills: int = 0
    decode_steps: int = 0
    recycles: int = 0
    occupancy_sum: int = 0  # sum over steps of active slots
    num_slots: int = 0
    done_polls: int = 0  # [B]-bool device->host fetches actually paid
    weight_pushes: int = 0  # mid-generation behavior refreshes applied
    released: int = 0  # placeholder rows force-finished on admission
    # chunked prefill (rollout.prefill_chunk > 0): chunks actually RUN
    # (the finish chunk included), prompt columns whose forward was
    # skipped (leading pad + pool-covered shared blocks), and the exact
    # dot-FLOPs those skipped columns would have cost (per-chunk cost
    # from the traced program — engine-7's counter, not an estimate)
    prefill_chunks: int = 0
    prefill_cols_skipped: int = 0
    prefill_flops_saved: float = 0.0
    # cross-request prefix sharing (serving tier): block-granular lookup
    # accounting per admitted real row — hits are blocks served from the
    # shared pool WITHOUT this row publishing them (true reuse), saved
    # counts the private-region writes skipped (hit + published blocks)
    prefix_lookup_blocks: int = 0
    prefix_hit_blocks: int = 0
    prefix_published_blocks: int = 0
    # speculative decoding (rollout.spec_decode): verify steps
    # dispatched, (row, step) pairs that proposed a draft, draft tokens
    # proposed/accepted (anchors excluded — they are ordinary decode
    # tokens), and the proposed lengths (the p50 gauge's sample set,
    # bounded by the phase's step count)
    spec_steps: int = 0
    spec_row_steps: int = 0
    spec_drafted: int = 0
    spec_accepted: int = 0
    spec_draft_lens: List[int] = dataclasses.field(default_factory=list)

    @property
    def slot_util(self) -> float:
        denom = self.num_slots * self.decode_steps
        return self.occupancy_sum / denom if denom else 0.0

    @property
    def prefix_hit_rate(self) -> float:
        if not self.prefix_lookup_blocks:
            return 0.0
        return self.prefix_hit_blocks / self.prefix_lookup_blocks

    @property
    def prefix_blocks_saved(self) -> int:
        """Private-region prefix blocks never written (served from or
        redirected into the shared pool)."""
        return self.prefix_hit_blocks + self.prefix_published_blocks

    @property
    def spec_accept_rate(self) -> float:
        if not self.spec_drafted:
            return 0.0
        return self.spec_accepted / self.spec_drafted

    @property
    def spec_tokens_per_step(self) -> float:
        """Tokens committed per drafted (row, step): the anchor (always
        accepted for a live row) plus the accepted draft prefix."""
        if not self.spec_row_steps:
            return 0.0
        return 1.0 + self.spec_accepted / self.spec_row_steps

    @property
    def spec_draft_len_p50(self) -> float:
        if not self.spec_draft_lens:
            return 0.0
        return float(np.median(self.spec_draft_lens))

    def to_dict(self) -> Dict[str, float]:
        return {
            "engine/admitted": float(self.admitted),
            "engine/completed": float(self.completed),
            "engine/prefills": float(self.prefills),
            "engine/decode_steps": float(self.decode_steps),
            "engine/slot_recycles": float(self.recycles),
            "engine/slot_util": round(self.slot_util, 4),
            "engine/done_polls": float(self.done_polls),
            "engine/weight_pushes": float(self.weight_pushes),
            "engine/released": float(self.released),
            "engine/prefill_chunks": float(self.prefill_chunks),
            "engine/prefill_cols_skipped": float(self.prefill_cols_skipped),
            "engine/prefill_flops_saved": float(self.prefill_flops_saved),
            "engine/prefix_hit_rate": round(self.prefix_hit_rate, 4),
            "engine/prefix_blocks_saved": float(self.prefix_blocks_saved),
            "engine/spec_draft_len_p50": round(self.spec_draft_len_p50, 4),
            "engine/spec_accept_rate": round(self.spec_accept_rate, 4),
            "engine/spec_tokens_per_step": round(
                self.spec_tokens_per_step, 4
            ),
        }


class ContinuousBatchingEngine:
    """Slot-admission decode over a paged KV cache.

    :param apply_fn: the model forward —
        ``apply_fn(params, input_ids, attention_mask, position_ids,
        cache, cache_index[, last_only]) -> {"logits", "cache"
        [, "values"]}`` (the same contract ``make_sampler`` consumes).
    :param init_cache_fn: ``(batch, capacity) -> linear KV buffers``
        (the family's ``init_cache``; the engine adds block tables).
    :param gen_config: generation parameters; the engine always samples
        per-row (``per_row_rng`` is forced on).
    :param num_slots: decode-slot pool size B.
    :param admit_width: static admission batch width (padded with dummy
        rows; one compiled prefill shape).
    :param harvest_width: completed rollouts per harvest group — the
        chunk size downstream consumers compile at. Must be <= num_slots.
    :param block_size: requested paged-KV block size (shrunk to divide
        Q + max_new_tokens).
    :param done_poll_interval: fetch the [B] ``done`` flags every k-th
        decode step (flags are sticky, so the latest fetch is exact);
        k=1 — the default — reproduces the poll-every-step loop
        bitwise, k>1 amortizes the host round-trip over k dispatches at
        the cost of up to k-1 idle steps per finished slot.
    :param mesh / param_shardings / cache_sharding: optional GSPMD
        pinning; ``cache_sharding`` shards the capacity axis (sp).
    :param prefix_pool_blocks: size (in blocks) of the cross-request
        shared-prefix KV pool (``inference/kv_cache.py``; managed by
        :class:`trlx_tpu.serving.prefix_cache.PrefixBlockPool`). 0 — the
        default, and the trainer collect path — disables sharing and
        keeps every jitted program byte-identical to the pool-less
        engine.
    :param stream_taps: make ``decode_step`` additionally return this
        step's (token, live) vectors so the host can stream tokens into
        per-request queues (:mod:`trlx_tpu.serving.streaming`) the step
        they are produced instead of at harvest. Off (the default) keeps
        the trainer-path program unchanged.
    :param prefill_chunk: chunked-prefill width in prompt columns
        (``rollout.prefill_chunk``; rounded by
        :func:`~trlx_tpu.inference.kv_cache.choose_prefill_chunk` to a
        block-aligned divisor of Q). ``> 0`` replaces the monolithic
        admission prefill with a scan over block-aligned prompt-column
        chunks, each wrapped in a ``lax.cond`` that SKIPS the forward
        when no row in the admit group needs it — leading all-pad
        columns of left-padded prompts (the mirror of PR-3's segmented
        decode early-exit: compute scales with
        ``ceil(max_real_len/chunk)`` instead of Q) and blocks served
        read-only from the shared-prefix pool (prefix sharing becomes a
        prefill-FLOP win, not just an HBM one). Chunk forwards attend a
        prompt-wide (Q) cache view instead of the full Q+R capacity —
        masked decode-region columns carry exactly-zero softmax weight,
        so the narrowing is bitwise-safe and the chunked prefill is
        token/mask-identical to the monolithic program (logprobs/values
        at the established bf16 resolution). 0 — the default, and the
        trainer collect path unless configured — keeps the monolithic
        program byte-identical.
    :param prefill_chunks_per_pump: with ``prefill_chunk > 0``, bound
        how many chunk forwards one :meth:`pump` iteration dispatches
        (Sarathi-style stall-free admission): a large admission burst
        spreads its prefill across pump iterations, each followed by a
        decode step for the already-running slots, instead of stalling
        decode for the whole burst. 0 = unbounded (a group's whole
        prefill dispatches in one pump, as the monolithic path does).
        :meth:`drive` (the trainer collect loop) always completes an
        admission inline regardless.
    :param spec_max_draft: speculative decoding (``rollout.spec_decode``,
        docs/inference.md): ``> 0`` adds a jitted ``verify_step`` program
        that forwards each slot's anchor sample plus up to this many
        host-drafted tokens in ONE pass and accepts the longest prefix
        where the target sample equals the draft — bitwise the one-token
        loop's tokens under the per-row RNG contract
        (``ops/sampling.py::accept_drafts``). Rows with no draft ride
        through with ``draft_len 0`` (anchor-only — exactly a decode
        step), and a round where nothing drafted dispatches the plain
        ``decode_step``. Forces :attr:`stream_taps` on: the host drafter
        needs per-step token visibility to keep its histories. 0 — the
        default, and every pre-existing path — builds no verify program
        and keeps all other programs byte-identical.
    :param spec_drafter: host-side drafter
        (:mod:`trlx_tpu.serving.spec_drafter` API: ``observe_context`` /
        ``observe_tokens`` / ``observe_accept`` / ``draft`` / ``forget``).
        ``None`` with ``spec_max_draft > 0`` builds the n-gram
        self-lookup drafter; the serving tier passes the trie drafter
        bound to its shared-prefix pool.
    :param spec_min_accept_ewma: accept-rate floor handed to the default
        drafter — a row/tenant whose acceptance EWMA falls below it
        stops drafting (graceful per-slot degrade to one-token decode,
        never an abort).
    """

    def __init__(
        self,
        *,
        apply_fn: Callable,
        init_cache_fn: Callable,
        gen_config: GenerationConfig,
        query_length: int,
        vocab_size: int,
        num_slots: int,
        admit_width: int = 0,
        harvest_width: int = 0,
        block_size: int = 16,
        done_poll_interval: int = 1,
        mesh=None,
        param_shardings=None,
        cache_sharding=None,
        with_values: bool = True,
        prefix_pool_blocks: int = 0,
        stream_taps: bool = False,
        prefill_chunk: int = 0,
        prefill_chunks_per_pump: int = 0,
        spec_max_draft: int = 0,
        spec_drafter=None,
        spec_min_accept_ewma: float = 0.0,
    ):
        from trlx_tpu.inference.kv_cache import choose_prefill_chunk

        self.gen_config = dataclasses.replace(gen_config, per_row_rng=True)
        self.Q = int(query_length)
        self.R = int(self.gen_config.max_new_tokens)
        self.capacity = self.Q + self.R
        self.vocab_size = int(vocab_size)
        self.num_slots = int(num_slots)
        self.block_size = choose_block_size(self.capacity, block_size)
        self.n_blocks = self.capacity // self.block_size
        self.prefix_pool_blocks = int(prefix_pool_blocks)
        if spec_max_draft < 0:
            raise ValueError(
                f"spec_max_draft={spec_max_draft} must be >= 0 (0 "
                "disables speculative decoding)"
            )
        # the verify window is draft + anchor; a draft wider than R-1
        # could never be fully accepted (per-position budget guard), so
        # shrink silently like choose_block_size does
        self.spec_max_draft = min(int(spec_max_draft), max(0, self.R - 1))
        self.spec_min_accept_ewma = float(spec_min_accept_ewma)
        self.spec_drafter = spec_drafter
        if self.spec_max_draft > 0 and self.spec_drafter is None:
            from trlx_tpu.serving.spec_drafter import NGramDrafter

            self.spec_drafter = NGramDrafter(
                max_draft=self.spec_max_draft,
                min_accept_ewma=self.spec_min_accept_ewma,
            )
        # spec decode needs per-step token visibility host-side (drafter
        # histories), which is exactly the streaming tap
        self.stream_taps = bool(stream_taps) or self.spec_max_draft > 0
        self.prefill_chunk = choose_prefill_chunk(
            self.Q, int(prefill_chunk), self.block_size
        )
        self.n_prefill_chunks = (
            self.Q // self.prefill_chunk if self.prefill_chunk else 0
        )
        self.prefill_chunks_per_pump = int(prefill_chunks_per_pump)
        if self.prefill_chunks_per_pump < 0:
            raise ValueError(
                f"prefill_chunks_per_pump={prefill_chunks_per_pump} "
                "must be >= 0 (0 = unbounded)"
            )
        if self.prefill_chunks_per_pump and not self.prefill_chunk:
            raise ValueError(
                "prefill_chunks_per_pump needs chunked prefill "
                "(prefill_chunk > 0) — there is nothing to budget on the "
                "monolithic program"
            )
        #: host callback ``{row: token_id} -> None`` fired per decode
        #: step with the step's live emissions (requires stream_taps)
        self.token_sink: Optional[Callable[[Dict[int, int]], None]] = None
        self.with_values = with_values
        self.done_poll_interval = int(done_poll_interval)
        if self.done_poll_interval < 1:
            raise ValueError(
                f"done_poll_interval={done_poll_interval} must be >= 1"
            )
        self._apply_fn = apply_fn
        self._init_cache_fn = init_cache_fn
        self.mesh = mesh
        shard = 1
        if mesh is not None:
            shape = dict(mesh.shape)
            shard = shape.get("dp", 1) * shape.get("fsdp", 1)
        self._shard = shard

        def round_up(n: int) -> int:
            return max(shard, ((n + shard - 1) // shard) * shard)

        self.admit_width = round_up(
            admit_width or max(1, self.num_slots // 4)
        )
        self.admit_width = min(self.admit_width, round_up(self.num_slots))
        self.harvest_width = round_up(harvest_width or self.admit_width)
        if self.harvest_width > self.num_slots:
            raise ValueError(
                f"harvest_width={self.harvest_width} cannot exceed "
                f"num_slots={self.num_slots} (a harvest group must fit "
                "in the pool or the drain deadlocks)"
            )
        if self.num_slots % shard:
            raise ValueError(
                f"num_slots={self.num_slots} must divide over the "
                f"{shard} data shards of the mesh"
            )

        fn_params = inspect.signature(apply_fn).parameters
        self._prefill_kwargs = (
            {"last_only": True} if "last_only" in fn_params else {}
        )
        # non-final prefill chunks only want the KV-cache side effect:
        # an apply_fn supporting ``skip_heads`` pays zero LM/value-head
        # FLOPs per chunk (models/heads.py); otherwise fall back to the
        # single-row last_only head
        self._chunk_kwargs = (
            {"skip_heads": True}
            if "skip_heads" in fn_params
            else dict(self._prefill_kwargs)
        )
        self._param_shardings = param_shardings
        self._cache_sharding = cache_sharding
        self._build_programs()

        # host bookkeeping (reset per phase)
        self._state: Optional[EngineState] = None
        self._params = None
        self._phase_key = None
        # queue entries: (ids, mask, row, shared_map|None,
        #                 publish_map|None, release)
        self._queue: List[Tuple] = []
        self._free: List[int] = []
        self._busy_rows: Dict[int, int] = {}  # slot -> row index
        self._done_slots: List[int] = []
        # chunked prefill: the admission group currently mid-prefill
        # (slots reserved, some chunk windows dispatched) — the serving
        # pump advances it by at most ``prefill_chunks_per_pump`` chunk
        # forwards per iteration; drive() completes it inline
        self._inflight_admission: Optional[Dict[str, Any]] = None
        self._chunk_flops: Optional[float] = None  # lazy exact per-chunk cost
        # spec decode: the next step's prefetched (draft, lens) host
        # arrays — invalidated by a weight push, an admission, or a
        # harvest (anything that changes what the pool is decoding)
        self._staged_drafts: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self._recycle_counts = np.zeros(self.num_slots, np.int64)
        self._next_row = 0
        # behavior-policy versioning (async actor–learner): every slot
        # records the params version it was admitted under; push_weights
        # stages a refresh that the drive loop applies at its safe point
        self.param_version = 0
        self._slot_versions = np.zeros(self.num_slots, np.int64)
        # staged (params, version) swapped as ONE reference under
        # _push_lock: push_weights arrives from the learner thread while
        # the drive thread's safe point applies it and
        # min_inflight_version reads it — staging two separate fields
        # can be observed torn (new params, old version tag), which
        # mis-tags every row admitted before the safe point
        self._pending_push: Optional[Tuple[Any, int]] = None
        self._push_lock = threading.Lock()
        self._steps_since_poll = 0
        #: host callback fired with the admitted rows' indices right
        #: after each prefill dispatch — the serving tier marks newly
        #: published prefix blocks ready for later admission groups here
        #: (dispatch order guarantees the device writes land first)
        self._admit_listener: Optional[Callable[[List[int]], None]] = None
        self.stats = EngineStats(num_slots=self.num_slots)
        # per-request latency bookkeeping (docs/observability.md,
        # "Serving metrics"): submit/admit/prefill/complete marks on the
        # shared telemetry clock, popped by the serving layer into its
        # latency histograms. Host dispatch timing — on an accelerator
        # the prefill mark is the dispatch wall, not device occupancy.
        self._req_times: Dict[int, Dict[str, float]] = {}
        #: request tracing (telemetry/request_trace.py): with this on,
        #: the host loop additionally logs one (dispatch time, admission
        #: epoch) pair per decode step and stamps done-poll marks, so
        #: the serving tier can emit per-request decode-cadence spans —
        #: the decode-step gap structure is the device-occupancy bound
        #: the dispatch-wall spans cannot give. Off (the default, and
        #: the trainer collect path) adds zero host work per step.
        self.trace_requests = False
        # the cadence log is PRUNED as rows harvest (entries below every
        # in-flight row's admit window drop; _step_base keeps the marks'
        # absolute indices valid) — a long-lived server's memory stays
        # bounded by its in-flight window, not its lifetime
        self._step_log: List[Tuple[float, int]] = []
        self._step_base = 0

    # ------------------------- jitted programs ------------------------- #

    def init_state(self) -> EngineState:
        """Fresh all-idle pool, committed to the engine's shardings.
        Idle slots are ``active=False, finished=True``:
        ``choose_tokens`` then emits deterministic (pad, 0, 0.0, 0.0)
        for them and their output/cache writes hit the out-of-bounds
        discard sentinel."""
        state = self._make_state()
        if self.mesh is not None:
            state = jax.device_put(state, self.state_sharding())
        return state

    def _make_state(self) -> EngineState:
        from trlx_tpu.inference.kv_cache import (
            empty_share_tables,
            identity_block_tables,
            init_shared_pool,
        )

        B, Q, R, V = self.num_slots, self.Q, self.R, self.vocab_size
        cfg = self.gen_config
        linear = self._init_cache_fn(B, self.capacity)
        tables = identity_block_tables(B, self.n_blocks)
        # one table array PER layer (logically shared, physically
        # distinct): the jitted programs donate the whole state, and XLA
        # refuses to donate one buffer appearing as several arguments
        cache = tuple(
            dict(layer, block_tables=jnp.array(tables)) for layer in linear
        )
        if self.prefix_pool_blocks > 0:
            def with_pool(layer):
                kv = layer["k"]
                pool = init_shared_pool(
                    self.prefix_pool_blocks,
                    self.block_size,
                    kv.shape[2],
                    kv.shape[3],
                    kv.dtype,
                    "int8" if "k_scale" in layer else "bfloat16",
                )
                return dict(
                    layer,
                    **pool,
                    shared_tables=empty_share_tables(B, self.n_blocks),
                    publish_tables=empty_share_tables(B, self.n_blocks),
                )

            cache = tuple(with_pool(layer) for layer in cache)
        return EngineState(
            cache=cache,
            row_keys=jnp.zeros((B, 2), jnp.uint32),
            t=jnp.zeros((B,), jnp.int32),
            n_real=jnp.zeros((B,), jnp.int32),
            logits_last=jnp.zeros((B, V), jnp.float32),
            value_last=jnp.zeros((B,), jnp.float32),
            active=jnp.zeros((B,), bool),
            finished=jnp.ones((B,), bool),
            out_tokens=jnp.full((B, R), cfg.pad_token_id, jnp.int32),
            out_mask=jnp.zeros((B, R), jnp.int32),
            out_logprobs=jnp.zeros((B, R), jnp.float32),
            out_values=jnp.zeros((B, R), jnp.float32),
            query_ids=jnp.zeros((B, Q), jnp.int32),
            query_mask=jnp.zeros((B, Q), jnp.int32),
            row_index=jnp.full((B,), -1, jnp.int32),
        )

    def state_sharding(self):
        """Sharding pytree for :class:`EngineState`: slot axis over
        dp×fsdp everywhere; cache K/V capacity axis additionally over sp
        when a ``cache_sharding`` was given (the LONGCTX layout); the
        shared-prefix pool (no slot axis — a broadcast structure every
        data shard reads) replicates."""
        from trlx_tpu.inference.kv_cache import SHARED_POOL_KEYS
        from trlx_tpu.parallel.mesh import batch_sharding, replicated

        batch_sh = batch_sharding(self.mesh)
        cache_sh = self._cache_sharding or batch_sh
        rep = replicated(self.mesh)

        def layer_sharding(layer: Dict[str, Any]) -> Dict[str, Any]:
            return {
                k: (
                    rep
                    if k in SHARED_POOL_KEYS
                    else (cache_sh if v.ndim == 4 else batch_sh)
                )
                for k, v in layer.items()
            }

        def pick(state: EngineState):
            cache = tuple(layer_sharding(l) for l in state.cache)
            other = {
                f.name: batch_sh
                for f in dataclasses.fields(EngineState)
                if f.name != "cache"
            }
            return EngineState(cache=cache, **other)

        # build from an abstract state so no buffers materialize here
        return pick(jax.eval_shape(self._make_state))

    def _build_programs(self) -> None:
        cfg = self.gen_config
        Q, R, cap, B = self.Q, self.R, self.capacity, self.num_slots
        nb, bs = self.n_blocks, self.block_size
        apply_fn = self._apply_fn
        with_values = self.with_values
        prefill_kwargs = self._prefill_kwargs

        def pin_cache(cache):
            if self._cache_sharding is None:
                return cache
            sh = self._cache_sharding
            return tuple(
                {
                    k: (
                        jax.lax.with_sharding_constraint(v, sh)
                        if v.ndim == 4
                        else v
                    )
                    for k, v in layer.items()
                }
                for layer in cache
            )

        sharing = self.prefix_pool_blocks > 0
        from trlx_tpu.inference.kv_cache import SHARED_POOL_KEYS

        def slice_group_cache(state, slot_ids, table_turns,
                              shared_map, publish_map):
            """The admitted slots' cache slice with freshly-rotated block
            tables (+ the group's share/publish maps and the whole pool
            when sharing) — shared by the monolithic prefill and every
            chunked-prefill call (one implementation, one parity
            surface). Recycled slots get a rotated table: physical block
            reuse order differs from logical order, so table resolution
            is exercised on every refill."""
            new_tables = (
                (jnp.arange(nb, dtype=jnp.int32)[None, :]
                 + table_turns[:, None])
                % nb
            )

            def slice_layer(layer):
                sl = {
                    k: jnp.take(v, slot_ids, axis=0)
                    for k, v in layer.items()
                    if k != "block_tables" and k not in SHARED_POOL_KEYS
                }
                sl["block_tables"] = new_tables
                if sharing:
                    # the pool is global — pass it whole; the admitted
                    # rows' share/publish assignments replace the
                    # recycled slots' stale metadata
                    for k in SHARED_POOL_KEYS:
                        if k in layer:
                            sl[k] = layer[k]
                    sl["shared_tables"] = shared_map
                    sl["publish_tables"] = publish_map
                return sl

            return tuple(slice_layer(l) for l in state.cache)

        def merge_group_cache(state, slot_ids, cache_out):
            def merge_layer(full, sl):
                def one(k):
                    if k in SHARED_POOL_KEYS:
                        # global pool: take the (possibly published-to)
                        # pool wholesale, never slot-scattered
                        return sl[k].astype(full[k].dtype)
                    return (
                        full[k]
                        .at[slot_ids]
                        .set(sl[k].astype(full[k].dtype), mode="drop")
                    )

                return {k: one(k) for k in full}

            return tuple(
                merge_layer(f, s) for f, s in zip(state.cache, cache_out)
            )

        def prefill(
            params,
            state: EngineState,
            slot_ids,  # [A] int32; num_slots = dummy (writes drop)
            prompt_ids,  # [A, Q] int32 left-padded
            prompt_mask,  # [A, Q] int32
            row_index,  # [A] int32 global draw index
            table_turns,  # [A] int32 block-table rotation per slot
            phase_key,  # [2] uint32
            shared_map=None,  # [A, nb] int32 pool block per logical
            publish_map=None,  # block (-1 = private / no publish)
        ) -> EngineState:
            A = prompt_ids.shape[0]
            row_keys = make_row_keys(phase_key, row_index)
            n_real = jnp.sum(prompt_mask, axis=-1).astype(jnp.int32)

            cache_slice = slice_group_cache(
                state, slot_ids, table_turns, shared_map, publish_map
            )
            cache_mask = concat_cols(
                prompt_mask, jnp.zeros((A, R), prompt_mask.dtype)
            )
            positions = jnp.clip(jnp.cumsum(prompt_mask, axis=-1) - 1, 0, None)
            out = apply_fn(
                params,
                prompt_ids,
                attention_mask=cache_mask,
                position_ids=positions,
                cache=cache_slice,
                cache_index=0,
                **prefill_kwargs,
            )
            logits_last = out["logits"][:, -1].astype(jnp.float32)
            if with_values:
                value_last = out["values"][:, -1].astype(jnp.float32)
            else:
                value_last = jnp.zeros((A,), jnp.float32)
            if cfg.max_length > 0:
                finished0 = n_real >= cfg.max_length
            else:
                finished0 = jnp.zeros((A,), bool)

            new_cache = merge_group_cache(state, slot_ids, out["cache"])

            def put(field, rows):
                return field.at[slot_ids].set(
                    rows.astype(field.dtype), mode="drop"
                )

            return dataclasses.replace(
                state,
                cache=pin_cache(new_cache),
                row_keys=put(state.row_keys, row_keys),
                t=put(state.t, jnp.zeros((A,), jnp.int32)),
                n_real=put(state.n_real, n_real),
                logits_last=put(state.logits_last, logits_last),
                value_last=put(state.value_last, value_last),
                active=put(state.active, jnp.ones((A,), bool)),
                finished=put(state.finished, finished0),
                out_tokens=put(
                    state.out_tokens,
                    jnp.full((A, R), cfg.pad_token_id, jnp.int32),
                ),
                out_mask=put(state.out_mask, jnp.zeros((A, R), jnp.int32)),
                out_logprobs=put(
                    state.out_logprobs, jnp.zeros((A, R), jnp.float32)
                ),
                out_values=put(
                    state.out_values, jnp.zeros((A, R), jnp.float32)
                ),
                query_ids=put(state.query_ids, prompt_ids),
                query_mask=put(state.query_mask, prompt_mask),
                row_index=put(state.row_index, row_index),
            )

        def decode_step(params, state: EngineState):
            """One token for every slot. Finished/idle slots ride along
            with deterministic pad emissions whose output and cache
            writes resolve out of bounds and drop."""
            if cfg.min_new_tokens > 0 or cfg.min_length > 0:
                min_new = jnp.maximum(
                    cfg.min_new_tokens, cfg.min_length - state.n_real
                )
            else:
                min_new = None
            token, live, logprob, value_out, finished = choose_tokens(
                cfg,
                state.logits_last,
                state.t,
                state.finished,
                state.value_last,
                state.n_real,
                min_new=min_new,
                row_keys=state.row_keys,
            )
            rows = jnp.arange(B, dtype=jnp.int32)
            # emissions land at [slot, t] for live rows; non-live rows
            # write at R (out of bounds -> dropped)
            w = jnp.where(live == 1, state.t, R)
            out_tokens = state.out_tokens.at[rows, w].set(token, mode="drop")
            out_mask = state.out_mask.at[rows, w].set(live, mode="drop")
            out_logprobs = state.out_logprobs.at[rows, w].set(
                logprob, mode="drop"
            )
            out_values = state.out_values.at[rows, w].set(
                value_out, mode="drop"
            )

            # forward the sampled token at per-row cache slot Q + t;
            # non-live rows write at capacity (dropped by the paged
            # cache's OOB sentinel)
            slot_pos = jnp.arange(cap)[None, :]
            cache_mask_t = (
                slot_pos <= Q + state.t[:, None]
            ).astype(jnp.int32) * concat_cols(
                state.query_mask, jnp.ones((B, R), state.query_mask.dtype)
            )
            cache_index = jnp.where(live == 1, Q + state.t, cap)
            out = apply_fn(
                params,
                token[:, None],
                attention_mask=cache_mask_t,
                position_ids=(state.n_real + state.t)[:, None],
                cache=state.cache,
                cache_index=cache_index,
            )
            new_logits = out["logits"][:, 0].astype(jnp.float32)
            new_value = (
                out["values"][:, 0].astype(jnp.float32)
                if with_values
                else jnp.zeros((B,), jnp.float32)
            )
            t_next = jnp.where(live == 1, state.t + 1, state.t)
            done = state.active & (finished | (t_next >= R))
            new_state = dataclasses.replace(
                state,
                cache=pin_cache(out["cache"]),
                t=t_next,
                logits_last=new_logits,
                value_last=new_value,
                finished=finished,
                out_tokens=out_tokens,
                out_mask=out_mask,
                out_logprobs=out_logprobs,
                out_values=out_values,
            )
            if self.stream_taps:
                # streaming decode: this step's emissions come home with
                # the done flags so the host can route tokens the step
                # they exist instead of at harvest (TTFT decouples from
                # harvest-group completion)
                return new_state, done, token, live
            return new_state, done

        def refill(state: EngineState, slot_ids):
            """Harvest ``slot_ids``'s finished rollouts and free the
            slots (the admission queue prefills them next poll)."""
            outs = {
                "query_tokens": jnp.take(state.query_ids, slot_ids, axis=0),
                "query_mask": jnp.take(state.query_mask, slot_ids, axis=0),
                "tokens": jnp.take(state.out_tokens, slot_ids, axis=0),
                "response_mask": jnp.take(state.out_mask, slot_ids, axis=0),
                "logprobs": jnp.take(state.out_logprobs, slot_ids, axis=0),
                "values": jnp.take(state.out_values, slot_ids, axis=0),
                "row_index": jnp.take(state.row_index, slot_ids, axis=0),
            }
            active = state.active.at[slot_ids].set(False, mode="drop")
            return dataclasses.replace(state, active=active), outs

        def release(state: EngineState, slot_ids):
            """Force-finish ``slot_ids`` right after admission: the next
            decode step emits the deterministic pad for them and flags
            them done, so a padding placeholder costs ONE decode step
            instead of decoding its full token budget (the serving
            tier's partial-harvest-group fix, docs/serving.md)."""
            finished = state.finished.at[slot_ids].set(True, mode="drop")
            return dataclasses.replace(state, finished=finished)

        # ------------- speculative verify (rollout.spec_decode) ------------ #
        D = self.spec_max_draft

        def verify_step(params, state: EngineState, draft, draft_len):
            """Drafted multi-token decode: sample each slot's anchor
            token from the carried logits (always the correct next token
            — all-rejected still commits it), forward the anchor plus up
            to D host-drafted tokens in ONE pass through the paged
            cache, and accept the longest draft prefix where the target
            sample equals the draft (``accept_drafts`` — bitwise the
            one-token loop's tokens under the per-row keys). Accepted
            emissions land exactly where sequential decode would put
            them; rejected/beyond-draft columns write at the per-column
            OOB sentinel and their outputs are never read (garbage KV
            above the accept frontier is either causally masked to
            exactly-zero softmax weight or overwritten by a later step's
            scatter before its first unmasked read). The carried
            logits/value are re-anchored at the LAST accepted column, so
            verify and decode steps mix freely over the same state."""
            if cfg.min_new_tokens > 0 or cfg.min_length > 0:
                min_new = jnp.maximum(
                    cfg.min_new_tokens, cfg.min_length - state.n_real
                )
            else:
                min_new = None
            token0, live0, lp0, v0, fin1 = choose_tokens(
                cfg,
                state.logits_last,
                state.t,
                state.finished,
                state.value_last,
                state.n_real,
                min_new=min_new,
                row_keys=state.row_keys,
            )
            T = D + 1
            col = jnp.arange(T, dtype=jnp.int32)[None, :]
            inputs = concat_cols(token0[:, None], draft)
            # per-column cache targets: anchor + valid draft columns land
            # at Q+t+j, everything else at capacity (per-column OOB drop
            # — the idle-slot sentinel applied columnwise)
            write_pos = jnp.where(
                (live0 == 1)[:, None] & (col <= draft_len[:, None]),
                Q + state.t[:, None] + col,
                cap,
            )
            slot_pos = jnp.arange(cap)[None, :]
            # window-wide validity mask: the causal bias (base column =
            # write_pos[:, 0]) narrows each query j to <= Q+t+j, and the
            # extra columns it excludes carry exactly-zero softmax
            # weight — bitwise the one-token step's attention per query
            cache_mask_t = (
                slot_pos <= Q + state.t[:, None] + D
            ).astype(jnp.int32) * concat_cols(
                state.query_mask, jnp.ones((B, R), state.query_mask.dtype)
            )
            out = apply_fn(
                params,
                inputs,
                attention_mask=cache_mask_t,
                position_ids=(state.n_real + state.t)[:, None] + col,
                cache=state.cache,
                cache_index=write_pos,
            )
            logits_seq = out["logits"].astype(jnp.float32)
            values_seq = (
                out["values"].astype(jnp.float32)
                if with_values
                else jnp.zeros((B, T), jnp.float32)
            )
            d_toks, d_acc, d_lps, d_vals, n_acc, fin = accept_drafts(
                cfg,
                logits_seq[:, :-1],
                values_seq[:, :-1],
                state.t,
                fin1,
                live0 == 1,
                state.n_real,
                draft,
                draft_len,
                state.row_keys,
                min_new=min_new,
                budget=R,
            )
            tokens_bt = concat_cols(token0[:, None], d_toks)
            acc_bt = concat_cols(live0[:, None], d_acc)
            lps_bt = concat_cols(lp0[:, None], d_lps)
            vals_bt = concat_cols(v0[:, None], d_vals)
            rows = jnp.arange(B, dtype=jnp.int32)[:, None]
            w = jnp.where(acc_bt == 1, state.t[:, None] + col, R)
            out_tokens = state.out_tokens.at[rows, w].set(
                tokens_bt, mode="drop"
            )
            out_mask = state.out_mask.at[rows, w].set(acc_bt, mode="drop")
            out_logprobs = state.out_logprobs.at[rows, w].set(
                lps_bt, mode="drop"
            )
            out_values = state.out_values.at[rows, w].set(
                vals_bt, mode="drop"
            )
            # re-anchor the decode invariant: logits/value at the last
            # accepted column predict the next un-emitted token
            new_logits = jnp.take_along_axis(
                logits_seq, n_acc[:, None, None], axis=1
            )[:, 0]
            new_value = jnp.take_along_axis(
                values_seq, n_acc[:, None], axis=1
            )[:, 0]
            t_next = state.t + live0 + n_acc
            done = state.active & (fin | (t_next >= R))
            new_state = dataclasses.replace(
                state,
                cache=pin_cache(out["cache"]),
                t=t_next,
                logits_last=new_logits,
                value_last=new_value,
                finished=fin,
                out_tokens=out_tokens,
                out_mask=out_mask,
                out_logprobs=out_logprobs,
                out_values=out_values,
            )
            return new_state, done, tokens_bt, acc_bt

        # ------------- chunked prefill (rollout.prefill_chunk) ------------- #
        # The monolithic `prefill` above pays full prompt-capacity
        # attention FLOPs for every admitted row. These two programs
        # replace it when prefill_chunk > 0:
        #
        # - `prefill_chunks`: lax.scan over the first n_chunks-1
        #   block-aligned prompt-column chunks, each under a lax.cond
        #   gated by the host-computed `need` vector — the run branch
        #   forwards W columns (heads skipped) and writes their KV
        #   through the block tables; the skip branch is the identity.
        #   With LEFT-padded prompts the skippable chunks are the
        #   LEADING ones (all-pad columns before the group's longest
        #   row starts, and blocks served read-only from the shared
        #   prefix pool), so this is the mirror of the segmented
        #   decode's early-exit tail: compute scales with
        #   ceil(max_real_len / W), not Q.
        # - `prefill_finish`: the final chunk, always run (every
        #   left-padded row's last real column lives there), producing
        #   logits_last/value_last and seeding the slot fields.
        #
        # Both pass the PROMPT-WIDE mask (width Q, not capacity) as the
        # attention view (ops/attention.py mask-width contract): prompt
        # queries never attend the decode region, whose masked columns
        # carry exactly-zero softmax weight in the monolithic program —
        # dropping them is bitwise-safe for tokens/masks and shrinks the
        # static attention FLOPs from Q·(Q+R) to Q·Q even before any
        # chunk is skipped. Skipped chunks leave their cache positions
        # zero; every read of those positions is masked (pad) or
        # overlaid from the shared pool, and a masked column's softmax
        # weight underflows to exactly 0.0 — so chunked and monolithic
        # prefill agree bitwise on tokens/masks (logprobs/values at the
        # established bf16 resolution; tests/test_chunked_prefill.py).
        W = self.prefill_chunk
        n_pc = self.n_prefill_chunks
        n_scan_chunks = max(0, n_pc - 1)
        chunk_kwargs = self._chunk_kwargs

        def prefill_chunks(
            params,
            state: EngineState,
            slot_ids,  # [A] int32; num_slots = dummy (writes drop)
            prompt_ids,  # [A, Q] int32 left-padded
            prompt_mask,  # [A, Q] int32
            table_turns,  # [A] int32 block-table rotation per slot
            need,  # [n_scan_chunks] bool — host plan ∩ pump window
            shared_map=None,  # [A, nb] int32 (sharing engines only)
            publish_map=None,
        ) -> EngineState:
            cache_slice = slice_group_cache(
                state, slot_ids, table_turns, shared_map, publish_map
            )
            positions = jnp.clip(
                jnp.cumsum(prompt_mask, axis=-1) - 1, 0, None
            )

            def body(cache, c):
                def run(cch):
                    ids_c = jax.lax.dynamic_slice_in_dim(
                        prompt_ids, c * W, W, axis=1
                    )
                    pos_c = jax.lax.dynamic_slice_in_dim(
                        positions, c * W, W, axis=1
                    )
                    out = apply_fn(
                        params,
                        ids_c,
                        attention_mask=prompt_mask,  # Q-wide view
                        position_ids=pos_c,
                        cache=cch,
                        cache_index=c * W,
                        **chunk_kwargs,
                    )
                    return out["cache"]

                return jax.lax.cond(need[c], run, lambda cch: cch, cache), None

            cache_slice, _ = jax.lax.scan(
                body, cache_slice, jnp.arange(n_scan_chunks)
            )
            return dataclasses.replace(
                state,
                cache=pin_cache(
                    merge_group_cache(state, slot_ids, cache_slice)
                ),
            )

        def prefill_finish(
            params,
            state: EngineState,
            slot_ids,
            prompt_ids,
            prompt_mask,
            row_index,
            table_turns,
            phase_key,
            shared_map=None,
            publish_map=None,
        ) -> EngineState:
            A = prompt_ids.shape[0]
            row_keys = make_row_keys(phase_key, row_index)
            n_real = jnp.sum(prompt_mask, axis=-1).astype(jnp.int32)
            cache_slice = slice_group_cache(
                state, slot_ids, table_turns, shared_map, publish_map
            )
            positions = jnp.clip(
                jnp.cumsum(prompt_mask, axis=-1) - 1, 0, None
            )
            off = Q - W  # static: the final chunk's column offset
            out = apply_fn(
                params,
                prompt_ids[:, off:],
                attention_mask=prompt_mask,  # Q-wide view
                position_ids=positions[:, off:],
                cache=cache_slice,
                cache_index=off,
                **prefill_kwargs,
            )
            logits_last = out["logits"][:, -1].astype(jnp.float32)
            if with_values:
                value_last = out["values"][:, -1].astype(jnp.float32)
            else:
                value_last = jnp.zeros((A,), jnp.float32)
            if cfg.max_length > 0:
                finished0 = n_real >= cfg.max_length
            else:
                finished0 = jnp.zeros((A,), bool)
            new_cache = merge_group_cache(state, slot_ids, out["cache"])

            def put(field, rows):
                return field.at[slot_ids].set(
                    rows.astype(field.dtype), mode="drop"
                )

            return dataclasses.replace(
                state,
                cache=pin_cache(new_cache),
                row_keys=put(state.row_keys, row_keys),
                t=put(state.t, jnp.zeros((A,), jnp.int32)),
                n_real=put(state.n_real, n_real),
                logits_last=put(state.logits_last, logits_last),
                value_last=put(state.value_last, value_last),
                active=put(state.active, jnp.ones((A,), bool)),
                finished=put(state.finished, finished0),
                out_tokens=put(
                    state.out_tokens,
                    jnp.full((A, R), cfg.pad_token_id, jnp.int32),
                ),
                out_mask=put(state.out_mask, jnp.zeros((A, R), jnp.int32)),
                out_logprobs=put(
                    state.out_logprobs, jnp.zeros((A, R), jnp.float32)
                ),
                out_values=put(
                    state.out_values, jnp.zeros((A, R), jnp.float32)
                ),
                query_ids=put(state.query_ids, prompt_ids),
                query_mask=put(state.query_mask, prompt_mask),
                row_index=put(state.row_index, row_index),
            )

        if self.mesh is not None and self._param_shardings is not None:
            from trlx_tpu.parallel.mesh import batch_sharding, replicated

            state_sh = self.state_sharding()
            batch_sh = batch_sharding(self.mesh)
            rep = replicated(self.mesh)
            prefill_in = [
                self._param_shardings,
                state_sh,
                rep,
                batch_sh,
                batch_sh,
                rep,
                rep,
                rep,
            ]
            if sharing:
                prefill_in += [rep, rep]  # shared_map, publish_map
            decode_out = (
                (state_sh, rep, rep, rep)
                if self.stream_taps
                else (state_sh, rep)
            )
            self.prefill_jit = jax.jit(
                prefill,
                in_shardings=tuple(prefill_in),
                out_shardings=state_sh,
                donate_argnums=(1,),
            )
            self.decode_step_jit = jax.jit(
                decode_step,
                in_shardings=(self._param_shardings, state_sh),
                out_shardings=decode_out,
                donate_argnums=(1,),
            )
            self.refill_jit = jax.jit(
                refill,
                in_shardings=(state_sh, rep),
                out_shardings=(state_sh, batch_sh),
                donate_argnums=(0,),
            )
            self.release_jit = jax.jit(
                release,
                in_shardings=(state_sh, rep),
                out_shardings=state_sh,
                donate_argnums=(0,),
            )
        else:
            self.prefill_jit = jax.jit(prefill, donate_argnums=(1,))
            self.decode_step_jit = jax.jit(decode_step, donate_argnums=(1,))
            self.refill_jit = jax.jit(refill, donate_argnums=(0,))
            self.release_jit = jax.jit(release, donate_argnums=(0,))

        self.prefill_chunks_jit = None
        self.prefill_finish_jit = None
        if self.prefill_chunk > 0:
            if self.mesh is not None and self._param_shardings is not None:
                from trlx_tpu.parallel.mesh import batch_sharding, replicated

                state_sh = self.state_sharding()
                batch_sh = batch_sharding(self.mesh)
                rep = replicated(self.mesh)
                chunks_in = [
                    self._param_shardings, state_sh, rep, batch_sh,
                    batch_sh, rep, rep,
                ]
                finish_in = [
                    self._param_shardings, state_sh, rep, batch_sh,
                    batch_sh, rep, rep, rep,
                ]
                if sharing:
                    chunks_in += [rep, rep]
                    finish_in += [rep, rep]
                if n_scan_chunks > 0:
                    self.prefill_chunks_jit = jax.jit(
                        prefill_chunks,
                        in_shardings=tuple(chunks_in),
                        out_shardings=state_sh,
                        donate_argnums=(1,),
                    )
                self.prefill_finish_jit = jax.jit(
                    prefill_finish,
                    in_shardings=tuple(finish_in),
                    out_shardings=state_sh,
                    donate_argnums=(1,),
                )
            else:
                if n_scan_chunks > 0:
                    self.prefill_chunks_jit = jax.jit(
                        prefill_chunks, donate_argnums=(1,)
                    )
                self.prefill_finish_jit = jax.jit(
                    prefill_finish, donate_argnums=(1,)
                )

        self.verify_step_jit = None
        if D > 0:
            if self.mesh is not None and self._param_shardings is not None:
                from trlx_tpu.parallel.mesh import batch_sharding, replicated

                state_sh = self.state_sharding()
                batch_sh = batch_sharding(self.mesh)
                rep = replicated(self.mesh)
                self.verify_step_jit = jax.jit(
                    verify_step,
                    in_shardings=(
                        self._param_shardings,
                        state_sh,
                        batch_sh,
                        batch_sh,
                    ),
                    out_shardings=(state_sh, rep, rep, rep),
                    donate_argnums=(1,),
                )
            else:
                self.verify_step_jit = jax.jit(
                    verify_step, donate_argnums=(1,)
                )

    # --------------------------- host loop ----------------------------- #

    def start_phase(self, params, phase_key, row_start: int = 0) -> None:
        """Reset the pool for a new collect phase. ``params`` is the
        frozen behavior policy every prefill/decode of the phase runs on
        (under the streamed phase: the trainer's behavior snapshot);
        ``phase_key`` seeds the per-row keys; ``row_start`` offsets the
        global draw index (usually 0 per phase)."""
        self._params = params
        self._phase_key = jnp.asarray(phase_key, jnp.uint32)
        self._state = self.init_state()
        self._queue = []
        self._free = list(range(self.num_slots))
        self._busy_rows = {}
        self._done_slots = []
        self._inflight_admission = None
        self._staged_drafts = None
        if self.spec_drafter is not None and hasattr(
            self.spec_drafter, "reset"
        ):
            self.spec_drafter.reset()
        self._recycle_counts[:] = 0
        self._next_row = row_start
        self.param_version = 0
        self._slot_versions[:] = 0
        with sched_points.guard(self._push_lock, "engine.push_lock"):
            self._pending_push = None
        self._steps_since_poll = 0
        self.stats = EngineStats(num_slots=self.num_slots)
        self._req_times = {}
        self._step_log = []
        self._step_base = 0

    def push_weights(self, params, version: Optional[int] = None) -> None:
        """Stage a refreshed behavior policy for in-flight application
        (PipelineRL-style mid-generation weight update). The swap itself
        happens at the drive loop's safe point — after harvest
        bookkeeping, before the next admission — NEVER here: a push
        landing between a harvest and its refill must not disturb the
        queued admit group or the freed-slot bookkeeping (the admission
        starvation edge pinned in tests/test_async_rl.py). Rows already
        decoding continue from their current position under the new
        params (their recorded per-token logprobs remain the true
        behavior logprobs — PPO's importance ratio corrects the rest);
        rows admitted after the swap are tagged with the new version.

        ``params`` must own its buffers (the learner's masters are
        donated by every train step — push a snapshot/copy, not the
        live tree). Consecutive pushes before the next safe point
        coalesce: only the newest params are ever applied.

        This is the engine's only cross-thread entry point: the staged
        (params, version) pair is one reference written under
        ``_push_lock`` so the drive thread can never observe new params
        with an old version tag."""
        sched_points.yield_point("engine.push")
        with sched_points.guard(self._push_lock, "engine.push_lock"):
            self._pending_push = (
                params,
                int(version) if version is not None
                else self.param_version + 1,
            )

    def _apply_pending_push(self) -> None:
        sched_points.yield_point("engine.safe_point")
        with sched_points.guard(self._push_lock, "engine.push_lock"):
            staged, self._pending_push = self._pending_push, None
            if staged is None:
                return
            self._params, self.param_version = staged
        # a weight push invalidates outstanding speculative drafts: the
        # next verify step's targets come from the refreshed params, so
        # prefetched proposals re-draft at the next step (drafts are
        # param-independent token guesses — dropping them affects accept
        # rate only, never correctness, but the invalidation keeps the
        # drafting overlap window inside one params version)
        self._staged_drafts = None
        self.stats.weight_pushes += 1

    def min_inflight_version(self) -> Optional[int]:
        """Oldest behavior version any not-yet-harvested work will carry:
        the min admission version over busy/done-awaiting-harvest slots,
        and — when prompts are still queued — the version they WILL be
        admitted under (the current one, or a staged push's). ``None``
        when nothing is in flight (the bounded-staleness guard is then
        vacuous)."""
        # the staged pair and the current version are read under the
        # push lock so a concurrent push_weights cannot be seen torn
        with sched_points.guard(self._push_lock, "engine.push_lock"):
            staged = self._pending_push
            current = self.param_version
        # _busy_rows covers decoding AND done-awaiting-harvest slots
        # (slots leave it only at harvest), so one pass covers both
        versions = [int(self._slot_versions[s]) for s in self._busy_rows]
        if self._queue:
            versions.append(staged[1] if staged is not None else current)
        return min(versions) if versions else None

    def submit(
        self,
        prompt_ids,
        prompt_mask,
        *,
        shared_maps=None,
        publish_maps=None,
        release: bool = False,
        submit_times=None,
    ) -> List[int]:
        """Enqueue prompts (host arrays, [n, Q]); returns their global
        row indices (draw order — the per-row RNG identity). Carries
        the ``engine.admit`` fault-injection site (resilience/chaos.py):
        an injected admission failure drives the orchestrator's
        fixed-sampler fallback and the server's admission retry.

        ``shared_maps`` / ``publish_maps`` ([n, n_blocks] int32, -1 =
        private) are the serving tier's per-row prefix-sharing
        assignments (requires ``prefix_pool_blocks > 0``);
        ``release=True`` marks the batch as padding placeholders that
        are force-finished the moment they are admitted (one decode
        step each instead of a full token budget); ``submit_times``
        (per-row floats on the telemetry clock) backdates the latency
        marks to when the request entered the SERVING tier, so
        ``serve/queue_wait_ms`` includes scheduler queueing, not just
        the slot-pool wait."""
        from trlx_tpu.resilience import chaos

        chaos.check("engine.admit")
        ids = np.asarray(prompt_ids)
        mask = np.asarray(prompt_mask)
        if ids.ndim != 2 or ids.shape[1] != self.Q:
            raise ValueError(
                f"submit expects [n, Q={self.Q}] prompt ids, got {ids.shape}"
            )
        if (
            shared_maps is not None or publish_maps is not None
        ) and self.prefix_pool_blocks < 1:
            raise ValueError(
                "prefix-sharing maps need an engine built with "
                "prefix_pool_blocks > 0"
            )
        rows = []
        t_submit = telemetry.monotonic()
        for i in range(ids.shape[0]):
            row = self._next_row
            self._next_row += 1
            self._queue.append((
                ids[i],
                mask[i],
                row,
                None if shared_maps is None else np.asarray(
                    shared_maps[i], np.int32
                ),
                None if publish_maps is None else np.asarray(
                    publish_maps[i], np.int32
                ),
                bool(release),
            ))
            self._req_times[row] = {
                "submitted": (
                    float(submit_times[i])
                    if submit_times is not None
                    else t_submit
                )
            }
            rows.append(row)
        return rows

    @property
    def pending(self) -> int:
        """Rows submitted but not yet harvested. ``_busy_rows`` covers
        decoding AND done-awaiting-harvest slots (``_done_slots`` is a
        subset of it until harvest pops both), so it is NOT added
        twice."""
        return len(self._queue) + len(self._busy_rows)

    def pop_request_timing(self, row: int) -> Optional[Dict[str, float]]:
        """The per-request latency decomposition for a HARVESTED row,
        in milliseconds — popped (each row reports once; un-popped rows
        are cleared at the next ``start_phase``):

        - ``queue_wait_ms``: submit → admission (slot-pool wait),
        - ``prefill_ms``: admission → first-token mark (the prefill
          dispatch that produces the row's first token),
        - ``ttft_ms``: submit → first token,
        - ``decode_ms``: first token → harvest,
        - ``e2e_ms``: submit → harvest.

        ``None`` for unknown/unfinished rows. Host dispatch timing on
        the shared telemetry clock; the serving layer divides
        ``decode_ms`` by the row's token count for per-token decode."""
        record = self.pop_request_record(row)
        return None if record is None else record["timing"]

    def pop_request_record(self, row: int) -> Optional[Dict[str, Any]]:
        """The full per-request trace record for a HARVESTED row — the
        ``timing`` decomposition of :meth:`pop_request_timing` plus the
        raw ``marks`` (submit/admit/first-token/done/completed seconds
        on the shared telemetry clock) and, under
        :attr:`trace_requests`, the row's decode-cadence slice:
        ``step_times`` (dispatch wall per decode step while the row was
        live) and ``step_epochs`` (the admission-prefill count at each
        step — an epoch change mid-row means the host loop interrupted
        this row's decode run to admit another group, which is exactly
        the bubble the trace analyzer attributes). Popped — each row
        reports once."""
        marks = self._req_times.get(row)
        if not marks or "completed" not in marks:
            return None
        self._req_times.pop(row, None)
        submitted = marks["submitted"]
        admitted = marks.get("admitted", submitted)
        first = marks.get("first_token", admitted)
        completed = marks["completed"]
        ms = 1000.0
        record: Dict[str, Any] = {
            "timing": {
                "queue_wait_ms": max(0.0, (admitted - submitted) * ms),
                "prefill_ms": max(0.0, (first - admitted) * ms),
                "ttft_ms": max(0.0, (first - submitted) * ms),
                "decode_ms": max(0.0, (completed - first) * ms),
                "e2e_ms": max(0.0, (completed - submitted) * ms),
            },
            "marks": dict(marks),
        }
        step_log = getattr(self, "_step_log", None)
        if step_log and "admit_step" in marks:
            base = getattr(self, "_step_base", 0)
            lo = max(0, int(marks["admit_step"]) - base)
            hi = min(
                int(marks.get("done_step", base + len(step_log))) - base,
                len(step_log),
            )
            window = step_log[lo:hi]
            record["step_times"] = [t for t, _ in window]
            record["step_epochs"] = [e for _, e in window]
        return record

    def _plan_chunk_need(self, prompt_mask, shared_map, publish_map):
        """[n_prefill_chunks] bool: which prompt-column chunks ANY row of
        the admit group actually needs computed. Column-granular: a
        column is needed when it is a real (non-pad) column not served
        read-only from the shared-prefix pool, or when its block is
        being PUBLISHED into the pool (the donor must compute what it
        publishes, pad columns included — readers gather the donor's
        bits). Leading all-pad chunks of a left-padded group and
        fully-pool-covered shared chunks come out un-needed. The same
        vector gates the jitted scan's ``lax.cond`` — host and device
        share one plan, so the skip accounting is transfer-free."""
        Q, W = self.Q, self.prefill_chunk
        mask = np.asarray(prompt_mask)
        first_real = Q - mask.sum(axis=1)
        cols = np.arange(Q)
        needed = cols[None, :] >= first_real[:, None]
        if shared_map is not None:
            bs = self.block_size
            col_blk = np.minimum(cols // bs, self.n_blocks - 1)
            covered = (shared_map[:, col_blk] >= 0) & (
                publish_map[:, col_blk] < 0
            )
            publishes = publish_map[:, col_blk] >= 0
            needed = (needed & ~covered) | publishes
        return needed.reshape(mask.shape[0], Q // W, W).any(2).any(0)

    def _begin_admission(self) -> None:
        """Reserve slots for the next ``admit_width`` group and stage its
        host arrays; the device dispatch happens in
        :meth:`_advance_admission` (one monolithic prefill call, or
        need-gated chunk windows plus the finish program)."""
        sharing = self.prefix_pool_blocks > 0
        nb_prompt = self.Q // self.block_size  # shareable prompt blocks
        with telemetry.span("collect/admit", force=True):
            A = self.admit_width
            take = min(len(self._free), len(self._queue), A)
            slots = [self._free.pop(0) for _ in range(take)]
            entries = [self._queue.pop(0) for _ in range(take)]
            prompt_ids = np.zeros((A, self.Q), np.int32)
            prompt_mask = np.zeros((A, self.Q), np.int32)
            slot_ids = np.full((A,), self.num_slots, np.int32)  # dummies
            row_index = np.zeros((A,), np.int32)
            turns = np.zeros((A,), np.int32)
            shared_map = np.full((A, self.n_blocks), -1, np.int32)
            publish_map = np.full((A, self.n_blocks), -1, np.int32)
            released_slots = []
            for i, (
                slot,
                (ids, mask, row, sh_row, pub_row, release),
            ) in enumerate(zip(slots, entries)):
                prompt_ids[i] = ids
                prompt_mask[i] = mask
                slot_ids[i] = slot
                row_index[i] = row
                turns[i] = self._recycle_counts[slot]
                self._busy_rows[slot] = row
                # behavior-version tag: the params this row's whole
                # prefill (and its first decode steps) run under
                self._slot_versions[slot] = self.param_version
                if release:
                    released_slots.append(slot)
                if sh_row is not None:
                    shared_map[i, : len(sh_row)] = sh_row
                if pub_row is not None:
                    publish_map[i, : len(pub_row)] = pub_row
                if sharing and not release:
                    hits = int(
                        np.sum(
                            (shared_map[i] >= 0) & (publish_map[i] < 0)
                        )
                    )
                    self.stats.prefix_lookup_blocks += nb_prompt
                    self.stats.prefix_hit_blocks += hits
                    self.stats.prefix_published_blocks += int(
                        np.sum(publish_map[i] >= 0)
                    )
                if self.spec_drafter is not None and not release:
                    # seed the drafter's per-row history with the real
                    # prompt tokens (left-padded: the mask selects them
                    # in order)
                    self.spec_drafter.observe_context(
                        row, [int(x) for x in np.asarray(ids)[
                            np.asarray(mask).astype(bool)
                        ]]
                    )
            args = (prompt_ids, prompt_mask)
            if self.mesh is not None:
                from trlx_tpu.parallel.mesh import batch_sharding

                args = jax.device_put(args, batch_sharding(self.mesh))
        self._inflight_admission = {
            "take": take,
            "entries": entries,
            "slot_ids": slot_ids,
            "row_index": row_index,
            "turns": turns,
            "ids": args[0],
            "mask": args[1],
            "shared_map": shared_map if sharing else None,
            "publish_map": publish_map if sharing else None,
            "released_slots": released_slots,
            "need": (
                self._plan_chunk_need(
                    prompt_mask,
                    shared_map if sharing else None,
                    publish_map if sharing else None,
                )
                if self.prefill_chunk > 0
                else None
            ),
            "next_chunk": 0,
            "chunk_walls": [],
            "t_admit": telemetry.monotonic(),
        }

    def _advance_admission(
        self, budget: Optional[int]
    ) -> Tuple[bool, int]:
        """Dispatch the in-flight admission's next slice of prefill work:
        the whole group (monolithic, or ``budget=None``), else at most
        ``budget`` chunk forwards (the serving pump's Sarathi-style
        stall-free bound — skipped chunks are free and never count).
        Returns ``(admission complete, chunk forwards dispatched)``."""
        adm = self._inflight_admission
        sharing = self.prefix_pool_blocks > 0
        map_args = []
        if sharing:
            map_args = [
                jnp.asarray(adm["shared_map"]),
                jnp.asarray(adm["publish_map"]),
            ]
        if self.prefill_chunk == 0:
            with telemetry.span(
                "collect/prefill", force=True, admitted=adm["take"]
            ):
                self._state = self.prefill_jit(
                    self._params,
                    self._state,
                    jnp.asarray(adm["slot_ids"]),
                    adm["ids"],
                    adm["mask"],
                    jnp.asarray(adm["row_index"]),
                    jnp.asarray(adm["turns"]),
                    self._phase_key,
                    *map_args,
                )
            self._finalize_admission()
            return True, 1
        n_scan = self.n_prefill_chunks - 1
        need = adm["need"]
        spent = 0
        if adm["next_chunk"] < n_scan:
            lo = adm["next_chunk"]
            idx = [c for c in range(lo, n_scan) if need[c]]
            if budget is not None and budget > 0 and len(idx) > budget:
                run = idx[:budget]
                hi = run[-1] + 1
            else:
                run = idx
                hi = n_scan
            if run:
                window = np.zeros((n_scan,), bool)
                window[run] = True
                with telemetry.span(
                    "collect/prefill", force=True,
                    admitted=adm["take"], chunks=len(run),
                ):
                    self._state = self.prefill_chunks_jit(
                        self._params,
                        self._state,
                        jnp.asarray(adm["slot_ids"]),
                        adm["ids"],
                        adm["mask"],
                        jnp.asarray(adm["turns"]),
                        jnp.asarray(window),
                        *map_args,
                    )
                self.stats.prefill_chunks += len(run)
                spent = len(run)
                adm["chunk_walls"].append(
                    (run[0] * self.prefill_chunk, telemetry.monotonic())
                )
            adm["next_chunk"] = hi
            if hi < n_scan or (budget is not None and spent >= budget):
                return False, spent
        # the finish chunk always runs: every left-padded row's last
        # real column lives there, and it produces logits_last
        with telemetry.span(
            "collect/prefill", force=True,
            admitted=adm["take"], chunks=1, finish=True,
        ):
            self._state = self.prefill_finish_jit(
                self._params,
                self._state,
                jnp.asarray(adm["slot_ids"]),
                adm["ids"],
                adm["mask"],
                jnp.asarray(adm["row_index"]),
                jnp.asarray(adm["turns"]),
                self._phase_key,
                *map_args,
            )
        self.stats.prefill_chunks += 1
        adm["chunk_walls"].append(
            ((self.n_prefill_chunks - 1) * self.prefill_chunk,
             telemetry.monotonic())
        )
        skipped = int(n_scan - np.count_nonzero(need[:n_scan]))
        self.stats.prefill_cols_skipped += skipped * self.prefill_chunk
        if skipped:
            # lazy one-time abstract trace — only ever paid once a group
            # actually skipped something (a no-skip serving workload
            # must not stall its first admission tracing the program
            # just to multiply the per-chunk cost by zero)
            self.stats.prefill_flops_saved += (
                skipped * self._chunk_flop_cost()
            )
        self._finalize_admission()
        return True, spent + 1

    def _finalize_admission(self) -> None:
        """Admission bookkeeping after the group's LAST prefill dispatch:
        placeholder release, latency marks, stats/gauges, and the admit
        listener (published prefix blocks become readable only now —
        every chunk that writes them has been dispatched)."""
        adm = self._inflight_admission
        self._inflight_admission = None
        sharing = self.prefix_pool_blocks > 0
        A = self.admit_width
        released_slots = adm["released_slots"]
        if released_slots:
            # padding placeholders: force-finish now so they cost
            # one decode step, not a full token budget. Fixed
            # admit_width call shape (num_slots = OOB dummy, the
            # scatter drops) — one compiled program regardless of
            # how many placeholders an admission carried.
            rel = np.full((A,), self.num_slots, np.int32)
            rel[: len(released_slots)] = released_slots
            self._state = self.release_jit(self._state, jnp.asarray(rel))
            self.stats.released += len(released_slots)
        # the last prefill dispatch computes the group's FIRST tokens,
        # so its dispatch end is the host-side time-to-first-token mark
        t_first = telemetry.monotonic()
        chunk_offsets = [
            {
                "col": int(col),
                "ms": round((t - adm["t_admit"]) * 1000.0, 3),
            }
            for col, t in adm["chunk_walls"]
        ]
        for entry in adm["entries"]:
            marks = self._req_times.get(entry[2])
            if marks is not None:
                marks["admitted"] = adm["t_admit"]
                marks["first_token"] = t_first
                if chunk_offsets:
                    # per-chunk-window dispatch offsets (column, ms after
                    # admission): the serve/prefill trace span carries
                    # these so --trace-report attributes chunked
                    # admissions (docs/observability.md)
                    marks["prefill_chunk_offsets"] = chunk_offsets
                if self.trace_requests:
                    # decode-cadence window start: this row's live
                    # steps begin at the current step-log position
                    # (absolute index — survives log pruning)
                    marks["admit_step"] = (
                        self._step_base + len(self._step_log)
                    )
        self.stats.prefills += 1
        self.stats.admitted += adm["take"]
        # new occupants joined the pool: a prefetched draft matrix no
        # longer covers it
        self._staged_drafts = None
        registry = telemetry.get_metrics()
        if sharing:
            registry.gauge("engine/prefix_hit_rate").set(
                self.stats.prefix_hit_rate
            )
            registry.gauge("engine/prefix_blocks_saved").set(
                self.stats.prefix_blocks_saved
            )
        if self.prefill_chunk > 0:
            registry.gauge("engine/prefill_chunks").set(
                float(self.stats.prefill_chunks)
            )
            registry.gauge("engine/prefill_cols_skipped").set(
                float(self.stats.prefill_cols_skipped)
            )
            registry.gauge("engine/prefill_flops_saved").set(
                float(self.stats.prefill_flops_saved)
            )
        if self._admit_listener is not None:
            self._admit_listener([e[2] for e in adm["entries"]])

    def _chunk_flop_cost(self) -> float:
        """Exact dot-FLOPs of ONE prefill chunk forward, read off the
        traced chunked program with engine-7's counter
        (``analysis/resource_audit.py::count_flops``: the scan body at
        its cond's run branch, times one). Traced lazily once per engine
        — abstract trace only, no compilation — so
        ``engine/prefill_flops_saved`` is a real FLOP number, not a
        heuristic; 0.0 when tracing is unavailable."""
        if self._chunk_flops is not None:
            return self._chunk_flops
        self._chunk_flops = 0.0
        n_scan = self.n_prefill_chunks - 1
        if (
            self.prefill_chunks_jit is None
            or n_scan < 1
            or self._params is None
        ):
            return self._chunk_flops
        try:
            from trlx_tpu.analysis.resource_audit import count_flops

            sds = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                self._params,
            )
            A, Q = self.admit_width, self.Q
            i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
            args = [
                sds,
                jax.eval_shape(self._make_state),
                i32(A),
                i32(A, Q),
                i32(A, Q),
                i32(A),
                jax.ShapeDtypeStruct((n_scan,), jnp.bool_),
            ]
            if self.prefix_pool_blocks > 0:
                args += [i32(A, self.n_blocks), i32(A, self.n_blocks)]
            closed = jax.make_jaxpr(self.prefill_chunks_jit)(*args)
            self._chunk_flops = count_flops(closed.jaxpr) / n_scan
        except Exception:  # pragma: no cover - accounting must never kill
            self._chunk_flops = 0.0
        return self._chunk_flops

    def _admit(self) -> None:
        """Complete every possible admission inline (the drive() /
        unbudgeted-pump path): one padded prefill per ``admit_width``
        group — monolithic, or the group's full chunk plan + finish."""
        if self._inflight_admission is not None:
            self._advance_admission(None)
        while self._free and self._queue:
            self._begin_admission()
            self._advance_admission(None)

    def _pump_admission(self, budget: int) -> None:
        """Advance admission by at most ``budget`` chunk forwards this
        pump iteration (``rollout.prefill_chunks_per_pump``): a large
        admission burst interleaves with decode steps instead of
        stalling them. A staged weight push applies only BETWEEN groups
        — a group's whole prefill runs under one params version (the
        version-tag contract push_weights documents)."""
        remaining = budget
        while remaining > 0:
            if self._inflight_admission is None:
                self._apply_pending_push()
                if not (self._free and self._queue):
                    return
                self._begin_admission()
            done, spent = self._advance_admission(remaining)
            remaining -= max(1, spent)
            if not done:
                return

    def _harvest_ready(self) -> Iterator[Dict[str, Any]]:
        """Yield fixed-width harvest groups while enough slots are done."""
        C = self.harvest_width
        while len(self._done_slots) >= C:
            slots = self._done_slots[:C]
            self._done_slots = self._done_slots[C:]
            with telemetry.span(
                "collect/slot_recycle", force=True, harvested=C
            ):
                self._state, outs = self.refill_jit(
                    self._state, jnp.asarray(slots, jnp.int32)
                )
            rows = [self._busy_rows.pop(s) for s in slots]
            versions = [int(self._slot_versions[s]) for s in slots]
            t_done = telemetry.monotonic()
            for r in rows:
                marks = self._req_times.get(r)
                if marks is not None:
                    marks["completed"] = t_done
            for s in slots:
                self._recycle_counts[s] += 1
                self._free.append(s)
            if self.spec_drafter is not None:
                for r in rows:
                    self.spec_drafter.forget(r)
                self._staged_drafts = None
            self.stats.recycles += C
            self.stats.completed += C
            outs = dict(outs)
            outs["rows"] = rows  # host-side draw indices, harvest order
            # host-side behavior-version tag per row (admission version):
            # the stream store's version column / staleness accounting
            outs["versions"] = versions
            if self.trace_requests:
                self._prune_step_log()
            yield outs

    def _prune_step_log(self) -> None:
        """Drop cadence-log entries no un-popped request can still
        reference (everything below the minimum in-flight ``admit_step``
        — un-admitted rows stamp at or past the current end, so they
        never constrain). ``_step_base`` keeps the retained marks'
        absolute indices valid. Bounds a long-lived server's cadence
        memory by its in-flight window instead of its lifetime."""
        if not self._step_log:
            return
        end = self._step_base + len(self._step_log)
        floor = min(
            (
                int(m["admit_step"])
                for m in self._req_times.values()
                if "admit_step" in m
            ),
            default=end,
        )
        drop = min(floor, end) - self._step_base
        if drop > 0:
            del self._step_log[:drop]
            self._step_base += drop

    def drive(self, target: int) -> Iterator[Dict[str, Any]]:
        """Run the admission/decode/harvest loop until ``target``
        completed rollouts have been yielded (in ``harvest_width``
        groups). ``target`` must be a multiple of ``harvest_width`` and
        must not exceed the submitted row count."""
        C = self.harvest_width
        if target % C:
            raise ValueError(
                f"target={target} must be a multiple of "
                f"harvest_width={C} (fixed-shape harvest groups)"
            )
        if target > self.pending + self.stats.completed:
            raise ValueError(
                f"drive(target={target}) but only {self.pending} rows "
                "are pending — submit the phase's prompts first"
            )
        yielded = 0
        self._steps_since_poll = 0
        while yielded < target:
            sched_points.yield_point("engine.drive")
            for group in self._harvest_ready():
                yield group
                yielded += len(group["rows"])
                if yielded >= target:
                    return
            # safe point for a staged weight push (async actor–learner):
            # harvest bookkeeping is settled and the queued admit group
            # is about to prefill under the refreshed params — a push
            # can never drop or reorder it. Never swap params while an
            # admission group is mid-prefill (chunked, pump-interleaved):
            # its chunks must all run under one version.
            if self._inflight_admission is None:
                self._apply_pending_push()
            self._admit()
            if not self._busy_rows:
                # nothing decoding and nothing harvestable: the queue
                # must be empty too (else _admit would have filled)
                raise RuntimeError(
                    "engine starved: no active slots and no full "
                    f"harvest group ({len(self._done_slots)} done < "
                    f"{C}) — target/harvest_width mismatch"
                )
            self._step_once()

    def _step_once(self) -> None:
        """Advance every slot one step: the drafted ``verify_step`` when
        spec decode is on and any slot proposed a draft, else the plain
        one-token ``decode_step`` (the fall-through — draftless rounds
        never pay the wider program)."""
        if self.spec_max_draft > 0:
            draft, lens = self._take_drafts()
            if lens.any():
                self._verify_once(draft, lens)
                return
        self._decode_once()

    def _take_drafts(self) -> Tuple[np.ndarray, np.ndarray]:
        """The next step's per-slot draft matrix: the prefetched stage
        if it survived (no weight push / admission / harvest since it
        was drafted), else drafted fresh."""
        if self._staged_drafts is not None:
            staged = self._staged_drafts
            self._staged_drafts = None
            return staged
        return self._draft_now()

    def _draft_now(self) -> Tuple[np.ndarray, np.ndarray]:
        """Ask the drafter for up to ``spec_max_draft`` proposed tokens
        per busy, not-yet-done slot. [B, D] int32 matrix + [B] lens."""
        D = self.spec_max_draft
        draft = np.zeros((self.num_slots, D), np.int32)
        lens = np.zeros((self.num_slots,), np.int32)
        if self.spec_drafter is None:
            return draft, lens
        done = set(self._done_slots)
        for slot, row in self._busy_rows.items():
            if slot in done:
                continue
            toks = self.spec_drafter.draft(row)
            if not toks:
                continue
            toks = list(toks)[:D]
            draft[slot, : len(toks)] = toks
            lens[slot] = len(toks)
        return draft, lens

    def _verify_once(self, draft: np.ndarray, lens: np.ndarray) -> None:
        """Dispatch one drafted verify step, land its accepted emissions
        into the drafter histories / stream taps, and prefetch the next
        step's drafts (host drafting overlaps the device's next work;
        the stage is dropped if a push/admission/harvest intervenes)."""
        self._state, done, toks, acc = self.verify_step_jit(
            self._params,
            self._state,
            jnp.asarray(draft),
            jnp.asarray(lens),
        )
        try:
            done.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
        self.stats.decode_steps += 1
        self.stats.spec_steps += 1
        self.stats.occupancy_sum += len(self._busy_rows)
        if self.trace_requests:
            self._step_log.append(
                (telemetry.monotonic(), self.stats.prefills)
            )
        tok_host = np.asarray(jax.device_get(toks))
        acc_host = np.asarray(jax.device_get(acc))
        for slot, row in self._busy_rows.items():
            n_cols = int(acc_host[slot].sum())  # anchor + accepted drafts
            if lens[slot]:
                n_drafted = int(lens[slot])
                n_accepted = max(0, n_cols - 1)
                self.stats.spec_row_steps += 1
                self.stats.spec_drafted += n_drafted
                self.stats.spec_accepted += n_accepted
                self.stats.spec_draft_lens.append(n_drafted)
                if self.spec_drafter is not None:
                    self.spec_drafter.observe_accept(
                        row, n_drafted, n_accepted
                    )
                marks = self._req_times.get(row)
                if marks is not None:
                    # ride the trace record: the serve/decode span's
                    # spec_segments/accepted attrs keep --trace-report's
                    # cadence estimator honest about multi-token steps
                    marks["spec_segments"] = (
                        marks.get("spec_segments", 0) + 1
                    )
                    marks["spec_accepted"] = (
                        marks.get("spec_accepted", 0) + n_accepted
                    )
            if n_cols and self.spec_drafter is not None:
                self.spec_drafter.observe_tokens(
                    row, [int(t) for t in tok_host[slot, :n_cols]]
                )
        if self.token_sink is not None:
            # route per accepted depth: each sink call keeps the
            # one-token {row: token} contract, in emission order
            for j in range(acc_host.shape[1]):
                emitted = {
                    row: int(tok_host[slot, j])
                    for slot, row in self._busy_rows.items()
                    if acc_host[slot, j]
                }
                if emitted:
                    self.token_sink(emitted)
        registry = telemetry.get_metrics()
        registry.gauge("engine/spec_accept_rate").set(
            self.stats.spec_accept_rate
        )
        registry.gauge("engine/spec_tokens_per_step").set(
            self.stats.spec_tokens_per_step
        )
        self._staged_drafts = self._draft_now()
        self._poll_done(done)

    def _decode_once(self) -> None:
        """Dispatch one decode step for the whole pool and run the
        amortized done-poll + streaming-tap bookkeeping."""
        if self.stream_taps:
            self._state, done, token, live = self.decode_step_jit(
                self._params, self._state
            )
        else:
            self._state, done = self.decode_step_jit(
                self._params, self._state
            )
            token = live = None
        try:
            done.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
        self.stats.decode_steps += 1
        self.stats.occupancy_sum += len(self._busy_rows)
        if self.trace_requests:
            # one (dispatch wall, admission epoch) pair per decode step:
            # the per-request cadence slice the trace analyzer turns
            # into host-loop/admission bubble estimates. Epoch = the
            # prefill count, so an epoch change inside a row's window
            # marks the admission that interrupted its decode run.
            self._step_log.append(
                (telemetry.monotonic(), self.stats.prefills)
            )
        need_tokens = (
            self.token_sink is not None or self.spec_drafter is not None
        )
        if token is not None and need_tokens:
            # streaming tap: route this step's live emissions to the
            # per-request queues NOW — time-to-first-token decouples
            # from harvest-group completion (the per-step fetch is the
            # streaming cost; non-streaming runs leave token_sink unset
            # and the unfetched outputs are dropped on device). Spec
            # decode reads the same tap to keep the drafter histories
            # current through draftless fall-through steps.
            tok_host = np.asarray(jax.device_get(token))
            live_host = np.asarray(jax.device_get(live))
            if self.spec_drafter is not None:
                for slot, row in self._busy_rows.items():
                    if live_host[slot]:
                        self.spec_drafter.observe_tokens(
                            row, [int(tok_host[slot])]
                        )
            if self.token_sink is not None:
                emitted = {
                    row: int(tok_host[slot])
                    for slot, row in self._busy_rows.items()
                    if live_host[slot]
                }
                if emitted:
                    self.token_sink(emitted)
        self._poll_done(done)

    def _poll_done(self, done) -> None:
        """Amortized done polling: the flags are sticky (a finished slot
        stays done until harvested), so fetching only every k-th step's
        flags is exact — k=1 reproduces the poll-every-step loop
        bitwise, and the async copy started at dispatch has k dispatches
        to land the transfer before the host reads it."""
        self._steps_since_poll += 1
        if self._steps_since_poll < self.done_poll_interval:
            return
        self._steps_since_poll = 0
        done_host = np.asarray(jax.device_get(done))
        self.stats.done_polls += 1
        # occupancy timeseries: one gauge sample per paid done-poll
        # (the registry's ring is bounded; one host call per poll)
        # — the Perfetto counter track rides these samples
        telemetry.get_metrics().gauge("engine/slot_util").set(
            self.stats.slot_util
        )
        t_done = telemetry.monotonic() if self.trace_requests else 0.0
        for slot, row in list(self._busy_rows.items()):
            if done_host[slot] and slot not in self._done_slots:
                self._done_slots.append(slot)
                if self.trace_requests:
                    # host-visible decode end: the harvest-wait stage
                    # (done → refill) starts here. With amortized
                    # polling (k>1) this lags the device by up to k-1
                    # steps — it is the host-observable bound.
                    marks = self._req_times.get(row)
                    if marks is not None:
                        marks["done"] = t_done
                        marks["done_step"] = (
                            self._step_base + len(self._step_log)
                        )

    # ------------------------- serving interface ----------------------- #

    @property
    def free_capacity(self) -> int:
        """Slots with neither an occupant nor a queued claim — how many
        more requests the serving scheduler may hand the engine without
        overcommitting the pool. Occupants are exactly ``_busy_rows``
        (which includes done-awaiting-harvest slots until the harvest
        pops them); counting ``_done_slots`` again would understate
        capacity and starve admission while a partial harvest group
        waits for peers."""
        return (
            self.num_slots
            - len(self._busy_rows)
            - len(self._queue)
        )

    def pump(self) -> List[Dict[str, Any]]:
        """One serving-loop iteration: harvest every ready fixed-width
        group, admit queued prompts into vacated slots, then advance
        decode one step. Returns the harvested groups (possibly empty).

        This is the scheduler-driven counterpart of :meth:`drive` — the
        serving tier interleaves QoS admission decisions between
        iterations instead of committing a whole phase's prompt set up
        front. Raises nothing on an idle pool (an empty pump is how the
        serving loop discovers it is drained).

        With ``prefill_chunk > 0`` and ``prefill_chunks_per_pump > 0``,
        one pump dispatches at most that many prefill-chunk forwards
        before advancing decode — a large admission burst spreads its
        prefill across pump iterations (Sarathi-style stall-free
        admission) instead of stalling every running slot for the whole
        burst."""
        groups = list(self._harvest_ready())
        if self.prefill_chunks_per_pump > 0:
            self._pump_admission(self.prefill_chunks_per_pump)
        else:
            if self._inflight_admission is None:
                self._apply_pending_push()
            self._admit()
        if self._busy_rows:
            self._step_once()
        return groups
