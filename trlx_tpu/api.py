"""One-call user API: ``trlx_tpu.train(...)``.

Re-design of ``trlx.train`` (``trlx/trlx.py:9-107``): same dispatch — a
``reward_fn`` selects the online PPO path, a reward-labeled ``dataset``
selects offline ILQL — and the same signature, with two deliberate fixes of
fork quirks (SURVEY §8): ``prompts``/``response_gt`` are real arguments
(the fork ignored ``prompts`` and hard-coded a samples.tsv path,
`trlx.py:46-54`), and nothing is read from disk implicitly.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Iterable, List, Optional, Tuple

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.utils.loading import get_orchestrator, get_pipeline, get_trainer

_DEFAULT_PPO_CONFIG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "configs",
    "ppo_sentiments.yml",
)
_DEFAULT_ILQL_CONFIG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "configs",
    "ilql_sentiments.yml",
)


def train(
    model_path: Optional[str] = None,
    reward_fn: Optional[Callable] = None,
    dataset: Optional[Tuple[Iterable[str], Iterable[float]]] = None,
    prompts: Optional[List] = None,
    response_gt: Optional[List[str]] = None,
    eval_prompts: Optional[List] = None,
    metric_fn: Optional[Callable] = None,
    config: Optional[TRLConfig] = None,
    split_token: Optional[str] = None,
    logit_mask=None,
    tokenizer=None,
):
    """Train a model with PPO (``reward_fn``) or ILQL (``dataset``).

    :param reward_fn: ``(samples, queries, response_gt) -> [float]`` — the
        fork's reward interface.
    :param dataset: (samples, rewards) for offline ILQL.
    :param prompts: strings (tokenized via ``tokenizer``) or token-id lists.
    :param response_gt: optional ground-truth responses carried to the
        reward fn (the fork's tsv pairs as a proper argument).
    """
    from trlx_tpu.ops.ilql_math import ILQLConfig

    if reward_fn is not None:
        config = config or TRLConfig.load_yaml(_DEFAULT_PPO_CONFIG)
        if isinstance(config.method, ILQLConfig):
            raise ValueError(
                "`reward_fn` selects online PPO, but the config's method is "
                "ILQLConfig — use a PPO method section (e.g. "
                "configs/ppo_sentiments.yml), or pass `dataset` for offline "
                "ILQL"
            )
        if model_path:
            config.model.model_path = model_path
        if prompts is None:
            raise ValueError("online PPO requires `prompts`")

        # One supervised attempt: build trainer/pipeline/orchestrator
        # fresh (after a failure, mid-phase state is assumed poisoned)
        # and run learn(). The resilience supervisor
        # (`train.resilience`, docs/resilience.md) restarts this on
        # retriable failures/preemptions, resuming from the latest good
        # checkpoint; disabled (the default) it runs exactly once.
        def attempt(resume: bool):
            config.train.resume_from_checkpoint = bool(resume)
            trainer = get_trainer(config.train.trainer)(
                config,
                reward_fn=reward_fn,
                metric_fn=metric_fn,
                tokenizer=tokenizer,
                logit_mask=logit_mask,
            )
            pipeline = get_pipeline(config.train.pipeline)(
                prompts,
                trainer.query_length,
                trainer.tokenizer,
                response_gt=response_gt,
            )
            orch = get_orchestrator(config.train.orchestrator)(
                trainer,
                pipeline,
                reward_fn=reward_fn,
                chunk_size=config.method.chunk_size,
            )

            if eval_prompts is None:
                # reuse the training pipeline (same prompts, same ground
                # truths — the reference's eval passes response_gt to the
                # reward fn, `accelerate_base_model.py:193`); create_loader
                # returns independent generators, so sharing the object is
                # safe and skips a second tokenize/decode pass over every
                # prompt
                eval_pipeline = pipeline
            else:
                # caller-supplied eval prompts carry no aligned gt list
                eval_pipeline = get_pipeline(config.train.pipeline)(
                    eval_prompts, trainer.query_length, trainer.tokenizer
                )
            # bind eval BEFORE the first collection: add_eval_pipeline may
            # expand the decode budget (bind_prompt_budget), and doing so
            # after make_experience would discard the just-compiled
            # sampler.
            trainer.add_eval_pipeline(eval_pipeline)
            # The first collection is learn()'s (it collects when the
            # buffer is empty): that way it runs as a streamed phase with
            # epoch-1 updates overlapping the decode
            # (docs/async_pipeline.md) instead of a plain serial
            # pre-collection here, and a resumed-finished run skips
            # collection entirely.
            # stop the background rollout writer when learn() finishes; a
            # write error the phase-end drain-on-exception flush swallowed
            # surfaces here — suppressed only when learn() itself is
            # raising (try/except/else rather than sys.exc_info() in a
            # finally: the latter also sees an *enclosing caller's*
            # in-flight exception and would silently drop the error on a
            # successful run)
            try:
                trainer.learn()
            except BaseException as e:
                # crash forensics for failures that escape learn()'s own
                # epilogue (e.g. a collect failure re-raised after the
                # stream abort): at most one flight dump per run — a no-op
                # when learn() already dumped or health is off
                trainer.flight_dump_on_exception(e)
                orch.close(reraise=False)
                raise
            orch.close()
            return trainer

        from trlx_tpu.resilience.supervisor import run_supervised

        return run_supervised(attempt, config)

    elif dataset is not None:
        samples, rewards = dataset
        samples, rewards = list(samples), list(rewards)
        config = config or TRLConfig.load_yaml(_DEFAULT_ILQL_CONFIG)
        if model_path:
            config.model.model_path = model_path
        # A reward-labeled dataset means offline ILQL. The method config is
        # the real discriminator: require it, then swap any leftover online
        # trainer/orchestrator (incl. seq2seq PPO variants) for the offline
        # pair, recorded back into the config so run logging stays truthful.
        if not isinstance(config.method, ILQLConfig):
            raise ValueError(
                "`dataset` selects offline ILQL, but the config's method is "
                f"{type(config.method).__name__} — use an ILQLConfig method "
                "section (e.g. configs/ilql_sentiments.yml)"
            )
        if config.train.trainer != "ILQLTrainer":
            config.train.trainer = "ILQLTrainer"
        if config.train.orchestrator != "OfflineOrchestrator":
            config.train.orchestrator = "OfflineOrchestrator"

        if eval_prompts is None:
            # derive eval prompts from the samples' prompt portions:
            # str -> itself; (prompt_str, response_str) -> prompt;
            # (token_list, action_start) -> tokens before the first action
            eval_prompts = []
            for s in samples[:64]:
                if isinstance(s, str):
                    eval_prompts.append(s)
                elif len(s) == 2 and isinstance(s[0], str):
                    eval_prompts.append(s[0])
                else:
                    toks, start = s
                    eval_prompts.append([int(t) for t in toks[: max(int(start), 1)]])

        # same supervised-attempt shape as the PPO branch: the offline
        # path has no rollout engine, but preemption drain + checkpoint
        # I/O retries + bounded auto-resume apply unchanged
        def attempt(resume: bool):
            config.train.resume_from_checkpoint = bool(resume)
            trainer = get_trainer(config.train.trainer)(
                config,
                metric_fn=metric_fn,
                tokenizer=tokenizer,
                logit_mask=logit_mask,
            )
            orch = get_orchestrator(config.train.orchestrator)(
                trainer, split_token=split_token
            )
            orch.make_experience(samples, rewards)
            eval_pipeline = get_pipeline(config.train.pipeline)(
                eval_prompts,
                trainer.query_length,
                trainer.tokenizer,
            )
            trainer.add_eval_pipeline(eval_pipeline)
            trainer.learn()
            return trainer

        from trlx_tpu.resilience.supervisor import run_supervised

        return run_supervised(attempt, config)

    raise ValueError("Either `reward_fn` (PPO) or `dataset` (ILQL) is required")
