"""Programmatic ``jax.profiler`` windows: one xplane trace per phase.

The legacy ``train.profile_dir`` path traced the first ~10 optimizer
steps from loop start — useful for cold-start triage, useless for "what
did phase 37 overlap with": by step 10 nothing interesting has streamed
yet, and tracing a whole run is gigabytes. ``train.profile_phase: N``
instead opens the profiler for EXACTLY phase N (one collect→train pair)
and closes it at the phase boundary, yielding one loadable xplane/
Perfetto artifact whose timeline lines up with the span tree the tracer
recorded for the same phase (shared wall-clock).

The stop fence (``block_until_ready``) sits at a phase boundary that
already synchronizes (the phase's stats were fetched), so the window
adds no new device syncs to the steady-state loop.
"""

from __future__ import annotations

from typing import Any, Optional


class PhaseProfiler:
    """Start/stop a ``jax.profiler`` trace around one phase.

    Drive with :meth:`on_phase_start` (before the phase's collection
    dispatches) and :meth:`on_phase_end` (after the phase's updates are
    consumed). Idempotent and crash-safe: :meth:`close` from a
    ``finally`` stops a still-open trace so an exception mid-phase
    cannot leak a running profiler into the next run."""

    def __init__(self, profile_dir: Optional[str], target_phase: Optional[int]):
        self.profile_dir = profile_dir or "profiles"
        self.target = target_phase
        self.active = False
        self.done = False

    @property
    def enabled(self) -> bool:
        return self.target is not None

    def on_phase_start(self, phase_index: int) -> None:
        if not self.enabled or self.active or self.done:
            return
        if phase_index != self.target:
            return
        import jax

        jax.profiler.start_trace(self.profile_dir)
        self.active = True

    def on_phase_end(self, sync: Any = None) -> None:
        """Close the window if one is open. ``sync`` (e.g. the train
        state's params) is blocked on first so in-flight device work of
        the profiled phase lands inside the trace — this boundary is
        already a sync point in every caller."""
        if not self.active:
            return
        import jax

        if sync is not None:
            jax.block_until_ready(sync)
        jax.profiler.stop_trace()
        self.active = False
        self.done = True  # exactly one window per run

    def close(self) -> None:
        if not self.active:
            return
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        self.active = False
