"""Runtime telemetry: span tracing, device metrics, profiler windows.

The static-analysis stack (engines 1–9) gates what a program *should*
cost before a run; this package watches the run itself:

- :mod:`trlx_tpu.telemetry.tracer` — low-overhead span tracer on one
  monotonic clock; the phase loop's single timing source (``with
  telemetry.span("phase/collect"): ...``), with per-name p50/p95 stats
  and a Perfetto/chrome-tracing JSONL exporter.
- :mod:`trlx_tpu.telemetry.device_metrics` — ``device.memory_stats()``
  sampling (live/peak HBM, transfer counters) logged next to the static
  engine-7 predictions so static-vs-measured gaps become a printed
  attribution.
- :mod:`trlx_tpu.telemetry.profiler` — programmatic ``jax.profiler``
  windows: ``train.profile_phase: N`` dumps one xplane trace for
  exactly phase N.
- :mod:`trlx_tpu.telemetry.health` — run-health monitoring: streaming
  training-dynamics detectors (kl-spike, entropy-collapse,
  ratio-explosion, grad-spike, reward-saturation, nan-precursor) over
  the per-update stats rows, enabled by ``train.health``.
- :mod:`trlx_tpu.telemetry.flight_recorder` — crash forensics: a
  bounded ring of phase records dumped as one JSON file on uncaught
  exceptions / detector policy / ``train.flight_dump_phase``;
  ``python -m trlx_tpu.telemetry --inspect <dump>`` renders the
  triage view.
- :mod:`trlx_tpu.telemetry.metrics` — typed rank-0 metrics registry
  (counters, gauges with sample rings, histograms) absorbing the
  ad-hoc stats dicts (``engine/*``, ``async/*``, ``mem/*``,
  ``serve/*``) into one snapshot-able namespace;
  ``telemetry.get_metrics()``.
- :mod:`trlx_tpu.telemetry.attribution` — measured MFU / HBM-BW
  utilization per traced program per phase window (engine-7 statics ÷
  span walls), async bubble breakdown, phase goodput — bench prints
  the table every round.
- :mod:`trlx_tpu.telemetry.run_ledger` — per-run manifests appended to
  a ledger JSONL; ``python -m trlx_tpu.telemetry --compare`` renders a
  movers diff between any two runs, ``--watch`` tails a live run's
  phase rows.

Engine 10 (``python -m trlx_tpu.analysis --perf-audit``) gates the
span durations against the ``perf_budgets`` section of
``analysis/budgets.json``. See docs/observability.md for the span
taxonomy and workflows.

The module-level :func:`span` / :func:`get_tracer` API routes through
one process-global tracer, enabled by default on the main process only
(rank-0 gating, like ``Logger``); ``TRLX_TELEMETRY=0/1`` overrides.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Optional

from trlx_tpu.telemetry.tracer import (  # noqa: F401
    DEFAULT_RING_SIZE,
    NULL_SPAN,
    Span,
    Tracer,
    chrome_counter_events,
    chrome_trace_events,
    chrome_trace_from_jsonl,
    env_ring_size,
    export_chrome_jsonl,
    monotonic,
    quantile,
)
from trlx_tpu.telemetry.metrics import (  # noqa: F401  (after tracer: shares its clock)
    NULL_INSTRUMENT,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    configure_metrics,
    flatten_snapshot,
    get_metrics,
    scoped_metrics,
    split_metric_label,
)

__all__ = [
    "DEFAULT_RING_SIZE",
    "NULL_SPAN",
    "Span",
    "Tracer",
    "chrome_counter_events",
    "chrome_trace_events",
    "chrome_trace_from_jsonl",
    "configure",
    "configure_from_dict",
    "configure_metrics",
    "env_ring_size",
    "export_chrome_jsonl",
    "get_metrics",
    "get_tracer",
    "monotonic",
    "now",
    "quantile",
    "scoped_metrics",
    "scoped_tracer",
    "span",
    "warn_on_span_drops",
]

_tracer: Optional[Tracer] = None


def _default_enabled() -> bool:
    env = os.environ.get("TRLX_TELEMETRY", "").lower()
    if env in ("0", "false", "off"):
        return False
    if env in ("1", "true", "on"):
        return True
    try:
        # rank-0 gating (multi-host pods trace on the main process only);
        # lazy so importing telemetry never forces jax initialization
        from trlx_tpu.parallel.distributed import is_main_process

        return is_main_process()
    except Exception:
        return True


def get_tracer() -> Tracer:
    """The process-global tracer (created on first use; ring capacity
    from ``TRLX_TELEMETRY_RING`` when set)."""
    global _tracer
    if _tracer is None:
        _tracer = Tracer(
            enabled=_default_enabled(), max_records=env_ring_size()
        )
    return _tracer


def span(name: str, force: bool = False, **attrs):
    """Open a span on the global tracer (see :meth:`Tracer.span`)."""
    return get_tracer().span(name, force=force, **attrs)


def now() -> float:
    """The shared monotonic clock, in seconds."""
    return monotonic()


@contextmanager
def scoped_tracer(tracer: Optional[Tracer] = None):
    """Temporarily install ``tracer`` (default: a fresh enabled one) as
    the process-global tracer; the previous tracer — records, enabled
    flag, everything — is restored on exit. Harnesses that drive
    instrumented code (the perf audit) use this so their measurement
    neither wipes nor leaks into the caller's span history."""
    global _tracer
    prev = get_tracer()
    installed = tracer if tracer is not None else Tracer(enabled=True)
    _tracer = installed
    try:
        yield installed
    finally:
        _tracer = prev


_drops_warned = False


def warn_on_span_drops(tracer: Optional[Tracer] = None) -> int:
    """Return the tracer's ``dropped`` count, warning ONCE on stderr
    when it is nonzero. Silent ring evictions skew every per-name p50
    (the oldest — often slowest, compile-bearing — spans vanish first),
    so any consumer aggregating span stats for a report should surface
    this; bench.py ships the count in its payload and calls this."""
    global _drops_warned
    t = tracer if tracer is not None else get_tracer()
    dropped = int(t.dropped)
    if dropped and not _drops_warned:
        import sys

        print(
            f"warning: span ring dropped {dropped} spans (oldest "
            "evicted) — per-name p50/p95 stats cover a truncated "
            "window; raise the ring with "
            "telemetry.configure(max_records=...)",
            file=sys.stderr,
        )
        _drops_warned = True
    return dropped


def configure(
    enabled: Optional[bool] = None, max_records: Optional[int] = None
) -> Tracer:
    """Adjust the global tracer; returns it. ``max_records`` resizes
    the ring (newest records kept; forced evictions count as dropped)."""
    tracer = get_tracer()
    if enabled is not None:
        tracer.enabled = bool(enabled)
    if max_records is not None:
        tracer.set_max_records(max_records)
    return tracer


def configure_from_dict(d) -> Tracer:
    """Apply the ``train.telemetry`` config section (and return the
    global tracer). One knob today — ``ring_size``, the span-ring
    capacity (per-request serving spans multiply span volume; an
    evicting ring truncates every trace the ``--trace-report`` analyzer
    reads). Unknown keys refuse loudly, like every other config section.
    Precedence: an explicit ``TRLX_TELEMETRY_RING`` env var wins over
    the config — the operator at the terminal outranks the YAML."""
    d = dict(d or {})
    known = {"ring_size"}
    unknown = set(d) - known
    if unknown:
        raise ValueError(
            f"Unknown train.telemetry keys: {sorted(unknown)} "
            f"(known: {sorted(known)})"
        )
    ring = d.get("ring_size")
    if ring is not None:
        # validate BEFORE precedence: a bad YAML value must refuse on
        # every machine, not only the ones without an env override
        ring = int(ring)
        if ring < 1:
            raise ValueError(
                f"train.telemetry.ring_size={ring} must be >= 1"
            )
        # a VALID env override wins; a malformed one (which
        # env_ring_size already ignores) must not ALSO block the
        # config — validity decides precedence, not mere presence
        raw = os.environ.get("TRLX_TELEMETRY_RING")
        try:
            env_valid = raw is not None and int(raw) > 0
        except ValueError:
            env_valid = False
        if not env_valid:
            return configure(max_records=ring)
    return get_tracer()
