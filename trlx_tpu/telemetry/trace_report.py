"""Critical-path analysis of per-request serving traces.

``python -m trlx_tpu.telemetry --trace-report <spans.jsonl>`` reads the
Perfetto JSONL the serving smokes/servers export (phase spans, counter
tracks, and the per-request chains of
:mod:`trlx_tpu.telemetry.request_trace` all share one file) and renders
three answers the aggregate ``serve/*`` histograms cannot give:

1. **Per-request critical path** — each completed request's end-to-end
   wall decomposed into the disjoint stages (queue wait, quota hold,
   prefill, decode, harvest wait, delivery). The stages are emitted
   contiguous on one mark chain, so their sum must equal the request's
   e2e up to clock-rounding — the per-request ``residual_pct`` column
   is the self-check (a big residual means a truncated or corrupted
   trace, e.g. span-ring eviction).
2. **Per-tenant / per-SLO-class tail breakdown** — which stage the p95
   request's latency is actually made of, per tenant and per SLO
   class: the triage answer "gold's tail is harvest-wait, not queue".
3. **Decode-cadence bubble estimate** — inter-decode-step dispatch
   gaps per request vs the trace's median step time. The host spans
   measure dispatch walls, not device occupancy (the documented
   attribution caveat); but a decode loop that dispatches every step
   back-to-back has near-constant cadence, so per-request *excess* gap
   over the median step is a measured bound on host-loop/admission
   bubbles — zero on a gap-free trace, and attributable (the
   ``serve/decode_segment`` epochs mark which admissions interrupted).

Pure host/stdlib; a viewer plus machine output (``--json``), never a
gate — CI asserts on the JSON.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

from trlx_tpu.telemetry.request_trace import ROOT, STAGES
from trlx_tpu.telemetry.tracer import quantile

#: residual above this fraction of e2e marks a request's chain broken
DEFAULT_RESIDUAL_TOLERANCE_PCT = 5.0


def load_request_spans(path: str) -> List[Dict[str, Any]]:
    """The request-trace events of one span JSONL: ``ph == "X"`` lines
    whose args carry a ``trace_id``. Other lines (phase spans, counter
    tracks, metadata, torn tails) are skipped, not fatal — one trace
    file serves many consumers."""
    events: List[Dict[str, Any]] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if not isinstance(ev, dict) or ev.get("ph") != "X":
                continue
            if not isinstance(ev.get("args"), dict):
                continue
            if "trace_id" not in ev["args"]:
                continue
            events.append(ev)
    return events


def build_requests(
    events: Sequence[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """Group request-trace events by ``trace_id`` into per-request
    views: root identity/attrs, per-stage ms sums, the decode-step
    offsets, and the residual self-check. Requests missing their root
    span are returned with ``complete=False`` (a truncated trace must
    be visible, never silently dropped)."""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    order: List[str] = []
    for ev in events:
        tid = str(ev["args"]["trace_id"])
        if tid not in by_trace:
            order.append(tid)
        by_trace.setdefault(tid, []).append(ev)
    out: List[Dict[str, Any]] = []
    for tid in order:
        evs = by_trace[tid]
        root = next((e for e in evs if e.get("name") == ROOT), None)
        stage_ms = {name: 0.0 for name in STAGES}
        step_offsets: List[float] = []
        spec_segments = 0
        spec_accepted = 0
        for e in evs:
            name = e.get("name", "")
            if name in stage_ms:
                stage_ms[name] += float(e.get("dur", 0.0)) / 1000.0
            if name == "serve/decode" and "step_offsets_ms" in e["args"]:
                step_offsets = [
                    float(x) for x in e["args"]["step_offsets_ms"]
                ]
            if name == "serve/decode" and "spec_segments" in e["args"]:
                spec_segments = int(e["args"]["spec_segments"])
                spec_accepted = int(e["args"].get("accepted", 0))
        view: Dict[str, Any] = {
            "trace_id": tid,
            "complete": root is not None,
            "stage_ms": {k: round(v, 3) for k, v in stage_ms.items()},
            "stage_sum_ms": round(sum(stage_ms.values()), 3),
            "step_offsets_ms": step_offsets,
            "spec_segments": spec_segments,
            "spec_accepted": spec_accepted,
        }
        if root is not None:
            args = root["args"]
            e2e = float(root.get("dur", 0.0)) / 1000.0
            view.update(
                tenant=str(args.get("tenant", "?")),
                slo_class=str(args.get("slo_class", "?")),
                status=str(args.get("status", "ok")),
                stream=bool(args.get("stream", False)),
                tokens=int(args.get("tokens", 0)),
                e2e_ms=round(e2e, 3),
                e2e_hist_ms=float(args.get("e2e_ms", e2e)),
                residual_pct=round(
                    abs(e2e - view["stage_sum_ms"])
                    / max(e2e, 1e-9)
                    * 100.0,
                    3,
                )
                if e2e > 0
                else 0.0,
                dominant_stage=max(
                    STAGES, key=lambda s: stage_ms[s]
                ),
            )
        out.append(view)
    return out


def tenant_tail_breakdown(
    requests: Sequence[Dict[str, Any]], key: str = "tenant"
) -> Dict[str, Dict[str, Any]]:
    """Per-``key`` (tenant or slo_class) tail summary: request count,
    e2e p50/p95 (nearest-rank, the repo's estimator), and the
    **dominant stage of the p95 request** — the stage its latency is
    mostly made of, which is what an operator pages on."""
    groups: Dict[str, List[Dict[str, Any]]] = {}
    for r in requests:
        if not r.get("complete"):
            continue
        groups.setdefault(str(r.get(key, "?")), []).append(r)
    out: Dict[str, Dict[str, Any]] = {}
    for name, rows in sorted(groups.items()):
        rows = sorted(rows, key=lambda r: r["e2e_ms"])
        durs = [r["e2e_ms"] for r in rows]
        ix = min(
            len(rows) - 1, max(0, int(round(0.95 * (len(rows) - 1))))
        )
        tail = rows[ix]
        out[name] = {
            "count": len(rows),
            "e2e_p50_ms": quantile(durs, 0.5),
            "e2e_p95_ms": quantile(durs, 0.95),
            "p95_dominant_stage": tail["dominant_stage"],
            "p95_dominant_ms": tail["stage_ms"][tail["dominant_stage"]],
        }
    return out


def decode_bubbles(
    requests: Sequence[Dict[str, Any]],
) -> Dict[str, Any]:
    """The decode-cadence device-bubble estimate. Per request the
    inter-step dispatch gaps come from its ``step_offsets_ms``; the
    reference cadence is the **median gap across the whole trace** (the
    phase's steady-state step time). A request's ``bubble_ms`` is its
    summed excess gap over that median — exactly zero on a gap-free
    trace (every gap == median), positive where the host loop stalled
    the cadence (admissions, harvests, GC, quota waits between pump
    iterations).

    Requests whose ``serve/decode`` span carries ``spec_segments > 0``
    ran speculative multi-token verify steps: their steps commit 1..D+1
    tokens each through a wider program, so neither "uniform cadence"
    nor "one token per step" holds and the excess-gap bound would read
    the verify steps themselves as host bubbles. They are excluded from
    both the median and the bubble rows and accounted explicitly
    (``n_spec_excluded``/``spec_tokens_accepted``) — never silently."""
    all_gaps: List[float] = []
    per_req: List[Dict[str, Any]] = []
    n_spec = 0
    spec_accepted = 0
    for r in requests:
        if r.get("spec_segments"):
            n_spec += 1
            spec_accepted += int(r.get("spec_accepted", 0))
            per_req.append({"trace_id": r["trace_id"], "gaps": []})
            continue
        offs = r.get("step_offsets_ms") or []
        gaps = [
            round(offs[i] - offs[i - 1], 3) for i in range(1, len(offs))
        ]
        per_req.append({"trace_id": r["trace_id"], "gaps": gaps})
        all_gaps.extend(gaps)
    median = quantile(sorted(all_gaps), 0.5) if all_gaps else 0.0
    rows: List[Dict[str, Any]] = []
    for r, g in zip(requests, per_req):
        if not g["gaps"]:
            continue
        bubble = sum(max(0.0, gap - median) for gap in g["gaps"])
        rows.append(
            {
                "trace_id": r["trace_id"],
                "tenant": r.get("tenant", "?"),
                "steps": len(g["gaps"]) + 1,
                "max_gap_ms": round(max(g["gaps"]), 3),
                "bubble_ms": round(bubble, 3),
            }
        )
    return {
        "median_step_ms": round(median, 3),
        "total_bubble_ms": round(
            sum(row["bubble_ms"] for row in rows), 3
        ),
        "requests": rows,
        "n_spec_excluded": n_spec,
        "spec_tokens_accepted": spec_accepted,
    }


def report_json(path: str) -> Dict[str, Any]:
    """The machine summary CI asserts on."""
    requests = build_requests(load_request_spans(path))
    complete = [r for r in requests if r.get("complete")]
    return {
        "requests": requests,
        "n_requests": len(requests),
        "n_complete": len(complete),
        "max_residual_pct": max(
            (r["residual_pct"] for r in complete), default=0.0
        ),
        "tenants": tenant_tail_breakdown(complete, "tenant"),
        "slo_classes": tenant_tail_breakdown(complete, "slo_class"),
        "bubbles": decode_bubbles(complete),
    }


def _fmt_ms(v: float) -> str:
    return f"{v:.1f}"


def render_report(
    path: str,
    tolerance_pct: float = DEFAULT_RESIDUAL_TOLERANCE_PCT,
) -> str:
    """The human triage view (same spirit as ``--inspect``)."""
    summary = report_json(path)
    requests = summary["requests"]
    lines: List[str] = []
    lines.append(
        f"trace report: {path}  requests={summary['n_requests']} "
        f"complete={summary['n_complete']}  "
        f"max_residual={summary['max_residual_pct']:.2f}%"
    )
    incomplete = [r for r in requests if not r.get("complete")]
    if incomplete:
        lines.append(
            f"WARNING: {len(incomplete)} request chain(s) have no root "
            "span — the span ring likely evicted (raise "
            "telemetry.ring_size / TRLX_TELEMETRY_RING)"
        )

    lines.append("")
    lines.append("critical path per request (ms):")
    short = {name: name.split("/", 1)[1] for name in STAGES}
    header = (
        f"  {'trace_id':24} {'tenant':10} {'slo':12} "
        + " ".join(f"{short[s]:>12}" for s in STAGES)
        + f" {'e2e':>10} {'resid%':>7}"
    )
    lines.append(header)
    for r in requests:
        if not r.get("complete"):
            lines.append(f"  {r['trace_id']:24} <no root span>")
            continue
        flag = (
            " !" if r["residual_pct"] > tolerance_pct else ""
        )
        lines.append(
            f"  {r['trace_id']:24} {r['tenant']:10} {r['slo_class']:12} "
            + " ".join(
                f"{_fmt_ms(r['stage_ms'][s]):>12}" for s in STAGES
            )
            + f" {_fmt_ms(r['e2e_ms']):>10} {r['residual_pct']:>6.2f}{flag}"
        )

    for key, title in (
        ("tenants", "per-tenant tail breakdown"),
        ("slo_classes", "per-SLO-class tail breakdown"),
    ):
        groups = summary[key]
        if not groups:
            continue
        lines.append("")
        lines.append(f"{title}:")
        lines.append(
            f"  {'group':14} {'count':>5} {'p50 ms':>10} {'p95 ms':>10} "
            f"  p95 dominant stage"
        )
        for name, row in groups.items():
            lines.append(
                f"  {name:14} {row['count']:>5} "
                f"{_fmt_ms(row['e2e_p50_ms']):>10} "
                f"{_fmt_ms(row['e2e_p95_ms']):>10}   "
                f"{row['p95_dominant_stage']} "
                f"({_fmt_ms(row['p95_dominant_ms'])} ms)"
            )

    bubbles = summary["bubbles"]
    lines.append("")
    lines.append(
        "decode-cadence bubbles (excess inter-step gap over the "
        f"trace median step {bubbles['median_step_ms']:.3f} ms; "
        "host-loop/admission stalls — a device-occupancy bound the "
        "dispatch spans cannot give):"
    )
    if bubbles["requests"]:
        lines.append(
            f"  {'trace_id':24} {'tenant':10} {'steps':>6} "
            f"{'max gap ms':>11} {'bubble ms':>10}"
        )
        for row in bubbles["requests"]:
            lines.append(
                f"  {row['trace_id']:24} {row['tenant']:10} "
                f"{row['steps']:>6} {row['max_gap_ms']:>11.3f} "
                f"{row['bubble_ms']:>10.3f}"
            )
        lines.append(
            f"  total bubble: {bubbles['total_bubble_ms']:.3f} ms"
        )
    else:
        lines.append("  no decode-cadence data (step offsets absent)")
    if bubbles.get("n_spec_excluded"):
        lines.append(
            f"  {bubbles['n_spec_excluded']} request(s) excluded: "
            "speculative verify steps commit multiple tokens per step "
            f"({bubbles['spec_tokens_accepted']} draft tokens accepted) "
            "— the uniform-cadence bound does not apply"
        )
    return "\n".join(lines)
