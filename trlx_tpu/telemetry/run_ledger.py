"""Run ledger: one manifest per run, a JSONL to diff them against.

The repo's measurement artifacts are latest-per-key JSON files
(``AB_*.json``, ``BENCH_rNN.json``) — good for "the current number",
useless for *mechanical* run-over-run comparison: nothing in-tree could
answer "what moved between yesterday's bench and today's" without a
human eyeballing two JSON blobs. The ledger closes that:

- :func:`build_manifest` — a :class:`RunManifest`-shaped dict capturing
  everything a later diff needs: config fingerprint, platform, git sha,
  span stats, the metrics-registry snapshot, health-event counts, the
  attribution table, and the producer's free-form payload (the BENCH
  record, an A/B record, a learn() summary).
- :func:`append_manifest` — append it as one JSONL line to the ledger
  (``TRLX_RUN_LEDGER`` env, or an explicit path). Append-only: the
  ledger is history, the AB artifacts stay the latest-per-key view.
- ``python -m trlx_tpu.telemetry --compare <run_a> <run_b>`` — resolve
  two runs (by run_id, ledger index, or manifest file path) and render
  the regression diff: numeric movers ranked by relative delta, span
  p50 deltas, attribution MFU deltas — the same triage style as
  ``--inspect``.
- ``--watch <run_dir>`` — tail the live ``phases.jsonl`` a training run
  mirrors its flight-phase records into (``train.run_dir``), one
  rendered row per phase, for long TPU runs you want to glance at
  without wandb.

Everything is host-side stdlib I/O; a failed ledger append must never
take down the run that produced the measurement (callers guard, and
:func:`append_manifest` only raises on programmer error).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, IO, List, Optional, Sequence

SCHEMA_VERSION = 1

#: env override for every default ledger path decision
LEDGER_ENV = "TRLX_RUN_LEDGER"
DEFAULT_LEDGER = "RUN_LEDGER.jsonl"


def default_ledger_path() -> str:
    return os.environ.get(LEDGER_ENV) or DEFAULT_LEDGER


def git_sha() -> str:
    """Short sha of the producing checkout ('' outside a repo / without
    git) — manifests self-identify the code that measured them."""
    try:
        return (
            subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True,
                text=True,
                timeout=5,
            ).stdout.strip()
        )
    except Exception:
        return ""


def _platform_info() -> Dict[str, Any]:
    from trlx_tpu.telemetry.flight_recorder import _platform_info as info

    return info()


def build_manifest(
    kind: str,
    run_id: Optional[str] = None,
    config: Optional[Dict[str, Any]] = None,
    payload: Optional[Dict[str, Any]] = None,
    attribution: Optional[Sequence[Dict[str, Any]]] = None,
    span_stats: Optional[Dict[str, Dict[str, float]]] = None,
    metrics: Optional[Dict[str, Any]] = None,
    health_events: Optional[Dict[str, int]] = None,
) -> Dict[str, Any]:
    """One run's manifest. ``span_stats`` and ``metrics`` default to the
    process-global tracer/registry state at call time (the epilogue
    callsite); pass explicit dicts when the caller already scoped its
    measurement window (bench's measured phases)."""
    from trlx_tpu import telemetry
    from trlx_tpu.telemetry.health import config_fingerprint

    if span_stats is None:
        try:
            span_stats = telemetry.get_tracer().stats()
        except Exception:
            span_stats = {}
    if metrics is None:
        try:
            metrics = telemetry.get_metrics().snapshot()
        except Exception:
            metrics = {}
    created = time.time()
    if run_id is None:
        run_id = (
            f"{kind}_{time.strftime('%Y%m%d_%H%M%S', time.localtime(created))}"
            f"_{os.getpid()}"
        )
    return {
        "schema_version": SCHEMA_VERSION,
        "run_id": run_id,
        "kind": kind,
        "created_unix": created,
        "date": time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(created)),
        "fingerprint": config_fingerprint(config) if config else "",
        "git_sha": git_sha(),
        "platform": _platform_info(),
        "span_stats": span_stats or {},
        "metrics": metrics or {},
        "health_events": dict(health_events or {}),
        "attribution": [dict(r) for r in (attribution or [])],
        "payload": dict(payload or {}),
    }


def numeric_payload(record: Dict[str, Any]) -> Dict[str, Any]:
    """The ledger-payload projection of a producer's record: plain
    numeric scalars only (bools excluded — they are flags, not
    measurements). One definition for every producer (bench, the A/B
    harnesses, the smoke, the learn() epilogue), so a change to the
    filtering rule lands everywhere at once."""
    return {
        k: float(v)
        for k, v in (record or {}).items()
        if isinstance(v, (int, float)) and not isinstance(v, bool)
    }


def append_manifest(
    manifest: Dict[str, Any], path: Optional[str] = None
) -> str:
    """Append one manifest line to the ledger; returns the path."""
    path = path or default_ledger_path()
    directory = os.path.dirname(path)
    if directory:
        os.makedirs(directory, exist_ok=True)
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(manifest, default=float) + "\n")
    return path


def load_ledger(path: str) -> List[Dict[str, Any]]:
    """Every parseable manifest line, oldest first (a torn final line —
    the run died mid-append — is skipped, not fatal)."""
    runs: List[Dict[str, Any]] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                runs.append(rec)
    return runs


def resolve_run(
    spec: str, ledger_path: Optional[str] = None
) -> Dict[str, Any]:
    """A run manifest from a CLI spec: a manifest ``.json`` file path, a
    ledger ``.jsonl`` path (its newest run), a ``run_id`` recorded in
    the ledger (latest wins on collision), a back-reference ``~1``
    (newest) / ``~2`` (previous) / ``last`` / ``prev`` — spelled with a
    tilde because argparse would eat a bare ``-1`` as an option — or an
    integer index into the ledger."""
    if os.path.exists(spec):
        if spec.endswith(".jsonl"):
            runs = load_ledger(spec)
            if not runs:
                raise ValueError(f"{spec}: empty ledger")
            return runs[-1]
        with open(spec, encoding="utf-8") as fh:
            return json.load(fh)
    path = ledger_path or default_ledger_path()
    if not os.path.exists(path):
        raise ValueError(
            f"run {spec!r} is not a file and ledger {path!r} does not "
            f"exist (set --ledger or ${LEDGER_ENV})"
        )
    runs = load_ledger(path)
    for rec in reversed(runs):
        if rec.get("run_id") == spec:
            return rec
    index: Optional[int] = None
    if spec == "last":
        index = -1
    elif spec == "prev":
        index = -2
    elif spec.startswith("~") and spec[1:].isdigit():
        index = -int(spec[1:])
    else:
        try:
            index = int(spec)
        except ValueError:
            index = None
    if index is not None:
        try:
            return runs[index]
        except IndexError:
            pass
    raise ValueError(
        f"run {spec!r} not found in {path} ({len(runs)} runs; specs: "
        "a run_id, ~1/~2/last/prev back-references, an integer index, "
        "or a manifest path)"
    )


def append_ab_manifest(kind: str, record: Dict[str, Any]) -> Optional[str]:
    """The A/B-harness recording path (``ab_*.py``): the latest-per-key
    artifact (``utils/ab_record.py``) stays the current-number view;
    this ALSO appends the measurement to the run ledger as history, so
    ``--compare`` can diff any two A/B rounds. Numeric payload only;
    best-effort (returns None on failure — a ledger hiccup must not
    fail a measurement that already printed)."""
    try:
        flat: Dict[str, Any] = numeric_payload(record)
        flat["metric"] = record.get("metric", "")
        return append_manifest(build_manifest(kind, payload=flat))
    except Exception as e:
        print(
            f"run_ledger: A/B manifest append failed "
            f"({type(e).__name__}: {e})",
            file=sys.stderr,
        )
        return None


# -------------------------------- compare --------------------------------- #


def flatten_numeric(manifest: Dict[str, Any]) -> Dict[str, float]:
    """One flat numeric view of a manifest for the movers diff: payload
    scalars, the flattened metrics snapshot, and per-span p50s."""
    from trlx_tpu.telemetry.metrics import flatten_snapshot

    out: Dict[str, float] = numeric_payload(manifest.get("payload") or {})
    for key, value in flatten_snapshot(manifest.get("metrics")).items():
        out[f"metrics/{key}"] = value
    for name, stats in (manifest.get("span_stats") or {}).items():
        if isinstance(stats, dict) and "p50_ms" in stats:
            out[f"span/{name}_p50_ms"] = float(stats["p50_ms"])
    for key, value in (manifest.get("health_events") or {}).items():
        out[f"health_events/{key}"] = float(value)
    return out


# one number formatter for the whole triage surface: --inspect and
# --compare must render values identically
from trlx_tpu.telemetry.flight_recorder import _fmt  # noqa: E402


def compare_runs(
    a: Dict[str, Any], b: Dict[str, Any], top: int = 20
) -> str:
    """The regression diff between two manifests (``a`` = baseline,
    ``b`` = candidate), rendered in the ``--inspect`` triage style:
    header, largest relative movers over the shared numeric keys, keys
    only one side has, and attribution MFU deltas."""
    lines: List[str] = []
    for tag, m in (("a", a), ("b", b)):
        platform = m.get("platform") or {}
        lines.append(
            f"run {tag}: {m.get('run_id', '?')}  [{m.get('kind', '?')}]  "
            f"{m.get('date', '')}  git={m.get('git_sha', '') or '?'}  "
            f"platform={platform.get('backend', '?')}"
            f"/{platform.get('device_kind', '?')}"
        )
    fp_a, fp_b = a.get("fingerprint", ""), b.get("fingerprint", "")
    if fp_a and fp_b and fp_a != fp_b:
        lines.append(
            f"WARNING: config fingerprints differ ({fp_a} vs {fp_b}) — "
            "the runs measured different configs; deltas below mix "
            "config changes with regressions"
        )
    pk_a = (a.get("platform") or {}).get("device_kind")
    pk_b = (b.get("platform") or {}).get("device_kind")
    if pk_a and pk_b and pk_a != pk_b:
        lines.append(
            f"WARNING: device kinds differ ({pk_a} vs {pk_b}) — "
            "wall-clock deltas are not comparable across backends"
        )

    flat_a, flat_b = flatten_numeric(a), flatten_numeric(b)
    shared = sorted(set(flat_a) & set(flat_b))
    movers = []
    for key in shared:
        va, vb = flat_a[key], flat_b[key]
        if va == vb:
            continue
        rel = (vb - va) / max(abs(va), 1e-9)
        movers.append((abs(rel), key, va, vb, rel))
    movers.sort(reverse=True)
    lines.append("")
    if movers:
        lines.append(f"movers (largest relative delta, top {top}):")
        for _mag, key, va, vb, rel in movers[:top]:
            lines.append(
                f"  {key:40} {_fmt(va):>12} -> {_fmt(vb):>12} "
                f"({rel * 100.0:+.1f}%)"
            )
    else:
        lines.append("movers: none (all shared numeric keys identical)")
    only_a = sorted(set(flat_a) - set(flat_b))
    only_b = sorted(set(flat_b) - set(flat_a))
    if only_a:
        lines.append(f"only in a: {', '.join(only_a[:12])}")
    if only_b:
        lines.append(f"only in b: {', '.join(only_b[:12])}")

    attr_a = {
        r.get("program"): r for r in (a.get("attribution") or [])
    }
    attr_b = {
        r.get("program"): r for r in (b.get("attribution") or [])
    }
    rows = []
    for program in sorted(set(attr_a) & set(attr_b)):
        ma, mb = attr_a[program].get("mfu"), attr_b[program].get("mfu")
        if ma is not None and mb is not None:
            rows.append((program, float(ma), float(mb)))
    if rows:
        lines.append("")
        lines.append("attribution: measured MFU per program:")
        for program, ma, mb in rows:
            lines.append(
                f"  {program:32} {_fmt(ma):>10} -> {_fmt(mb):>10}"
            )
    return "\n".join(lines)


# --------------------------------- watch ---------------------------------- #


def phases_path(run_dir_or_file: str) -> str:
    """``--watch`` target resolution: a directory means its
    ``phases.jsonl``; an explicit ``.jsonl`` file is taken as-is. A
    path that does not exist YET is treated as a run directory too —
    watching before the training run creates it is the headline
    use-case, and resolving it to the bare name would tail the
    directory itself once it appears (IsADirectoryError)."""
    if os.path.isfile(run_dir_or_file) or (
        run_dir_or_file.endswith(".jsonl")
        and not os.path.isdir(run_dir_or_file)
    ):
        return run_dir_or_file
    return os.path.join(run_dir_or_file, "phases.jsonl")


def render_phase_row(row: Dict[str, Any]) -> str:
    """One live phase record as one terminal line: identity, the
    headline stats, span p50s, and any tripped events."""
    stats = row.get("stats") or {}
    spans = row.get("spans") or {}
    parts = [f"phase {row.get('phase', '?'):>4}"]
    if row.get("step") is not None:
        parts.append(f"step {row['step']}")
    for key in (
        "losses/total_loss",
        "policy/mean_rollout_kl",
        "exp/scores_mean",
        "health/entropy",
    ):
        if key in stats:
            parts.append(f"{key.split('/', 1)[1]}={_fmt(float(stats[key]))}")
    for name in ("phase/collect", "phase/train"):
        if name in spans:
            parts.append(
                f"{name.split('/', 1)[1]}={float(spans[name].get('p50_ms', 0)):.0f}ms"
            )
    events = row.get("events") or []
    if events:
        dets = sorted({e.get("detector", "?") for e in events})
        parts.append(f"events: {','.join(dets)}")
    mem = row.get("memory") or {}
    if "peak_bytes_in_use" in mem:
        parts.append(f"hbm_peak={mem['peak_bytes_in_use'] / 2**30:.2f}G")
    return "  ".join(parts)


def watch(
    run_dir_or_file: str,
    follow: bool = True,
    poll_s: float = 1.0,
    out: Optional[IO[str]] = None,
) -> int:
    """Tail a run's live phase rows, rendering each as one line.
    ``follow=False`` renders what is on disk and returns (the testable
    core); ``follow=True`` polls until interrupted. Returns the number
    of rows rendered."""
    out = out or sys.stdout
    path = phases_path(run_dir_or_file)
    rendered = 0
    pos = 0
    printed_waiting = False
    while True:
        if os.path.exists(path):
            with open(path, encoding="utf-8") as fh:
                fh.seek(pos)
                while True:
                    line = fh.readline()
                    if not line:
                        break
                    if not line.endswith("\n") and follow:
                        break  # torn tail: re-read on the next poll
                    pos = fh.tell()
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    print(render_phase_row(row), file=out)
                    rendered += 1
        elif not follow:
            raise FileNotFoundError(path)
        elif not printed_waiting:
            print(f"watching {path} (not created yet)...", file=out)
            printed_waiting = True
        if not follow:
            return rendered
        try:
            time.sleep(poll_s)
        except KeyboardInterrupt:
            return rendered


class PhaseLogWriter:
    """Append-one-JSON-line-per-phase mirror of the flight recorder's
    phase records into ``<run_dir>/phases.jsonl`` — the ``--watch``
    feed. Opens/closes per append (a phase boundary is seconds apart;
    durability beats a held handle that a preemption would tear)."""

    def __init__(self, run_dir: str):
        self.run_dir = run_dir
        self.path = os.path.join(run_dir, "phases.jsonl")
        self._warned = False

    def append(self, row: Dict[str, Any]) -> None:
        try:
            os.makedirs(self.run_dir, exist_ok=True)
            with open(self.path, "a", encoding="utf-8") as fh:
                fh.write(json.dumps(row, default=float) + "\n")
        except OSError as e:
            if not self._warned:
                print(
                    f"run_ledger: cannot append phase row to "
                    f"{self.path} ({e}) — live --watch feed disabled",
                    file=sys.stderr,
                )
                self._warned = True
