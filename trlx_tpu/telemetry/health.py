"""Run-health monitoring: training-dynamics detectors over the stats stream.

PR 6 made the *machine* observable (spans, HBM, profiler windows); this
module watches the *learning*. The failure modes that end RLHF runs —
KL blowups, entropy collapse, PPO ratio explosions, gradient spikes,
reward saturation, the slow slide into NaN — all announce themselves in
the per-update stats rows long before the loss curve looks wrong. Today
those rows go to wandb and a human maybe reads them tomorrow; the
:class:`HealthMonitor` reads them the moment they are fetched.

Design constraints, in order:

- **zero extra device traffic**: the monitor only ever consumes values
  that are *already on host* — the stats rows every train path fetches
  in its one batched ``device_get``. A value that is still a
  ``jax.Array`` is skipped, never forced (the one-transfer discipline
  of PR 1 is load-bearing; ``tests/test_health.py`` pins the count).
  The extra *device-side* scalars (entropy under ``ent_coef=0``,
  log-ratio extremes, value explained-variance, reward quantiles) are
  fused into the jitted step's stats pytree by ``ops/ppo_math.py`` /
  ``ops/ilql_math.py`` under the same ``health`` flag, so they ride the
  existing transfer.
- **bitwise-inert**: ``health.enabled`` must not perturb training.
  Detectors are pure host arithmetic over fetched floats; the device
  stats are extra outputs of the step, never inputs to the loss
  (pinned in ``tests/test_phase_overlap.py``).
- **streaming**: each watched series keeps an EWMA mean/variance and a
  bounded window — O(1) per observation, no growing state, robust to
  the per-minibatch cadence differing across train paths.

A tripped rule emits a structured :class:`HealthEvent` into the Logger
(one ``health_event`` JSON line), the span stream (a zero-length
``health/<id>`` span, so trips land on the trace timeline next to the
phase that produced them), and — at ``error`` severity — the
``health.on_error`` policy: ``warn`` (default), ``dump`` (write a
flight-recorder forensics file), or ``abort`` (dump, then raise
:class:`HealthAbort`).

Rank-0 only, like ``Logger``: on multi-host pods the monitor runs on
the main process (a per-host ``abort`` decision could desynchronize
the collective schedule — the ``host-branch`` rule's hazard — so the
policy fires where the stats are logged).

See docs/observability.md ("Run-health monitoring") for the detector
taxonomy and tuning table.
"""

from __future__ import annotations

import hashlib
import json
import math
from collections import deque
from dataclasses import dataclass, field, fields
from typing import Any, Dict, List, Optional, Sequence, Tuple


class HealthAbort(RuntimeError):
    """Raised by the ``health.on_error: abort`` policy after the flight
    dump is written — crash-fast instead of training on into garbage."""


#: severity levels, weakest first
SEVERITIES = ("info", "warning", "error")

#: stat-key prefixes the nan-precursor rule scans (everything numeric the
#: step reports about the model's dynamics)
NAN_WATCH_PREFIXES = (
    "losses/", "policy/", "values/", "returns/", "advantages/",
    "optimizer/", "health/",
)

#: The detector registry: id -> spec. ``series`` lists candidate stat
#: keys (every candidate present in a row is evaluated against its own
#: per-key state — different train paths surface different keys).
#: Kinds:
#:   zscore   — value spikes ``zmax`` sigmas above its EWMA (armed after
#:              ``warmup`` observations; absolute floor ``min_abs`` so
#:              microscopic series can't trip on noise)
#:   collapse — value drops below ``frac`` x its EWMA baseline, baseline
#:              itself above ``min_baseline`` (armed after warmup)
#:   above    — value exceeds an absolute ``threshold`` (always armed)
#:   flatline — value stays below ``eps`` for ``patience`` consecutive
#:              observations (armed after warmup)
#:   nonfinite— any watched stat is NaN/Inf or exceeds ``huge`` in
#:              magnitude (always armed; the precursor fires on the huge
#:              value BEFORE check_anomalies sees the NaN it becomes)
DEFAULT_DETECTORS: Dict[str, Dict[str, Any]] = {
    "kl-spike": dict(
        series=("policy/mean_rollout_kl", "policy/approx_kl"),
        kind="zscore", severity="error", zmax=8.0, min_abs=0.05,
    ),
    "entropy-collapse": dict(
        series=("health/entropy",),
        kind="collapse", severity="error", frac=0.4, min_baseline=0.2,
    ),
    "ratio-explosion": dict(
        series=("health/log_ratio_max",),
        kind="above", severity="error", threshold=4.0,
    ),
    "grad-spike": dict(
        series=("optimizer/grad_norm",),
        kind="zscore", severity="warning", zmax=12.0, min_abs=1.0,
    ),
    "reward-saturation": dict(
        series=("health/reward_std", "exp/score_std"),
        kind="flatline", severity="warning", eps=1e-6, patience=8,
    ),
    "nan-precursor": dict(
        series=(), kind="nonfinite", severity="error", huge=1e8,
    ),
    # async actor–learner circuit-breaker (docs/async_pipeline.md): the
    # per-phase max rollout staleness (learner updates ahead of the
    # oldest consumed row's behavior policy). The version-lag guard
    # should make a breach impossible; a trip therefore means the guard
    # or the version tagging is broken — error severity so the
    # health.on_error policy (warn/dump/abort) is the breaker. The
    # effective threshold is injected from train.async_rl's
    # staleness_window when async RL is enabled (BaseRLTrainer._setup_
    # health); the registry default never trips on its own.
    "staleness-breach": dict(
        series=("async/staleness",),
        kind="above", severity="error", threshold=1e9,
    ),
    # serving-tier SLO watch (docs/serving.md): the serving loop feeds
    # one row per harvest group with the measured queue-wait p95 over
    # each tenant's SLO-class budget, keyed per tenant
    # (serve/slo_queue_wait_ratio[tenant=acme] — matched by PREFIX
    # since tenant names are dynamic). A ratio > 1 means that tenant's
    # requests waited longer than its class promises; warning severity
    # (a breach wants scheduling/capacity attention, not an abort), and
    # it flows through the same event sinks as every detector.
    "slo-breach": dict(
        series=(), series_prefix=("serve/slo_queue_wait_ratio",),
        kind="above", severity="warning", threshold=1.0,
    ),
}


@dataclass
class HealthConfig:
    """``train.health`` section (plain dict in YAML, parsed here).

    :param enabled: master switch — off (the default) keeps every jitted
        program and stats row byte-identical to a pre-health build.
    :param on_error: policy for ``error``-severity trips: ``warn`` logs,
        ``dump`` writes a flight-recorder forensics file, ``abort``
        dumps then raises :class:`HealthAbort`.
    :param window: recent-values window per series (event context) and
        the EWMA half-life scale (alpha = 2/(window+1)).
    :param warmup: observations per series before z-score/collapse/
        flatline rules arm (startup transients must not trip).
    :param cooldown: observations a tripped detector+series stays quiet
        after an event (one anomaly = one event, not one per row).
    :param flight_capacity: phase records the flight ring retains.
    :param dump_dir: directory flight dumps are written into.
    :param detectors: per-id parameter overrides, e.g.
        ``{"kl-spike": {"zmax": 12.0}}``.
    :param disable: detector ids to turn off.
    """

    enabled: bool = False
    on_error: str = "warn"
    window: int = 32
    warmup: int = 8
    cooldown: int = 16
    flight_capacity: int = 16
    dump_dir: str = "health_dumps"
    max_events: int = 256
    detectors: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    disable: List[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, config: Optional[Dict[str, Any]]) -> "HealthConfig":
        config = dict(config or {})
        known = {f.name for f in fields(cls)}
        unknown = set(config) - known
        if unknown:
            raise ValueError(
                f"Unknown train.health keys: {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        out = cls(**config)
        if out.on_error not in ("warn", "dump", "abort"):
            raise ValueError(
                f'train.health.on_error={out.on_error!r} must be one of '
                f'"warn" | "dump" | "abort"'
            )
        for did in list(out.detectors) + list(out.disable):
            if did not in DEFAULT_DETECTORS:
                raise ValueError(
                    f"unknown health detector {did!r}; known: "
                    f"{sorted(DEFAULT_DETECTORS)}"
                )
        for did, overrides in out.detectors.items():
            # same loudness as the top-level keys: a tuning typo
            # ("zmx") silently keeping the old threshold is worse than
            # a refusal. series/kind are structural, not tunable.
            tunable = set(DEFAULT_DETECTORS[did]) - {
                "series", "series_prefix", "kind",
            }
            unknown_params = set(overrides) - tunable
            if unknown_params:
                raise ValueError(
                    f"unknown keys for health detector {did!r}: "
                    f"{sorted(unknown_params)} (tunable: {sorted(tunable)})"
                )
            severity = overrides.get("severity")
            if severity is not None and severity not in SEVERITIES:
                # a misspelled severity would silently never match the
                # on_error policy's `== "error"` filter
                raise ValueError(
                    f"health detector {did!r}: severity {severity!r} "
                    f"must be one of {SEVERITIES}"
                )
        return out


def config_fingerprint(config_dict: Dict[str, Any]) -> str:
    """Short stable hash of a run config — stamped into every event and
    flight dump so forensics files self-identify which config produced
    them (two dumps with different fingerprints are not comparable)."""
    blob = json.dumps(config_dict, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:12]


@dataclass
class HealthEvent:
    """One detector trip. ``window`` carries the recent series values
    (newest last) so the dump/inspect view shows the run-up, not just
    the offending point."""

    detector: str
    severity: str
    series: str
    value: float
    step: int
    phase: Optional[int]
    message: str
    fingerprint: str = ""
    zscore: Optional[float] = None
    baseline: Optional[float] = None
    threshold: Optional[float] = None
    window: List[float] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "detector": self.detector,
            "severity": self.severity,
            "series": self.series,
            "value": self.value,
            "step": self.step,
            "phase": self.phase,
            "message": self.message,
            "fingerprint": self.fingerprint,
            "window": list(self.window),
        }
        for key in ("zscore", "baseline", "threshold"):
            v = getattr(self, key)
            if v is not None:
                out[key] = v
        return out


class _SeriesState:
    """EWMA mean/variance + bounded recent window for one stat key."""

    __slots__ = ("count", "mean", "var", "window", "flat_run")

    def __init__(self, window: int):
        self.count = 0
        self.mean = 0.0
        self.var = 0.0
        self.window: "deque[float]" = deque(maxlen=window)
        self.flat_run = 0  # consecutive sub-eps observations (flatline)

    def std(self) -> float:
        return math.sqrt(max(self.var, 0.0))

    def update(self, value: float, alpha: float) -> None:
        self.count += 1
        if self.count == 1:
            self.mean = value
            self.var = 0.0
        else:
            delta = value - self.mean
            self.mean += alpha * delta
            # EW variance of the residual around the moving mean
            self.var = (1.0 - alpha) * (self.var + alpha * delta * delta)
        self.window.append(value)


def _host_float(value: Any) -> Optional[float]:
    """``value`` as a host float, or None when it is not already host-side.

    The monitor must NEVER force a device transfer: a ``jax.Array``
    (anything exposing device shards) is skipped here and observed later
    from the fetched row it eventually lands in."""
    if isinstance(value, (bool,)):
        return None
    if isinstance(value, (int, float)):
        return float(value)
    # numpy scalars / 0-d arrays without importing numpy at module top
    if type(value).__module__.startswith("numpy"):
        try:
            return float(value)
        except (TypeError, ValueError):
            return None
    return None


class HealthMonitor:
    """Streaming detector engine over per-update/per-phase stats rows.

    ``observe`` is the whole API: feed it every host-side stats row in
    arrival order; it returns the :class:`HealthEvent` list that row
    tripped (usually empty). State is per stat key, so rows of different
    shapes (update rows, orchestrator collect rows) interleave freely.
    """

    def __init__(self, config: Optional[HealthConfig] = None,
                 fingerprint: str = ""):
        self.config = config or HealthConfig(enabled=True)
        self.fingerprint = fingerprint
        self._alpha = 2.0 / (max(int(self.config.window), 2) + 1.0)
        self._series: Dict[str, _SeriesState] = {}
        # cooldown horizon per (detector, series) — per the config
        # contract "one anomaly = one event": keyed by BOTH so one
        # detector's trip cannot silence a different detector watching
        # the same key (a grad-spike warning must not mask a NaN)
        self._quiet: Dict[Tuple[str, str], int] = {}
        self._observations = 0
        self.events: List[HealthEvent] = []
        self.event_counts: Dict[str, int] = {}
        self.latest: Dict[str, float] = {}
        self._specs: Dict[str, Dict[str, Any]] = {}
        for did, spec in DEFAULT_DETECTORS.items():
            if did in self.config.disable:
                continue
            merged = dict(spec)
            merged.update(self.config.detectors.get(did, {}))
            self._specs[did] = merged

    # ---------------------------- checkpointing ---------------------------- #

    def state_dict(self) -> Dict[str, Any]:
        """Resume-carried detector state. The EWMA baselines, warmup
        counts, flatline runs, and cooldown horizons all feed whether
        the next observation trips an event: a monitor rebuilt empty
        after a supervisor restart would re-warm from scratch and stay
        silent through exactly the post-resume steps most likely to
        regress. Everything here is host JSON scalars — safe for the
        checkpoint metadata pickle."""
        return {
            "series": {
                key: {
                    "count": st.count,
                    "mean": st.mean,
                    "var": st.var,
                    "window": list(st.window),
                    "flat_run": st.flat_run,
                }
                for key, st in sorted(self._series.items())
            },
            "quiet": [
                [detector, series, horizon]
                for (detector, series), horizon in sorted(self._quiet.items())
            ],
            "observations": self._observations,
            "event_counts": dict(self.event_counts),
            "latest": dict(self.latest),
            "events": [ev.to_dict() for ev in self.events],
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._series = {}
        for key, st_state in state["series"].items():
            st = _SeriesState(self.config.window)
            st.count = int(st_state["count"])
            st.mean = float(st_state["mean"])
            st.var = float(st_state["var"])
            st.window.extend(float(v) for v in st_state["window"])
            st.flat_run = int(st_state["flat_run"])
            self._series[key] = st
        self._quiet = {
            (detector, series): int(horizon)
            for detector, series, horizon in state["quiet"]
        }
        self._observations = int(state["observations"])
        self.event_counts = {
            k: int(v) for k, v in state["event_counts"].items()
        }
        self.latest = {k: float(v) for k, v in state["latest"].items()}
        self.events = [HealthEvent(**ev) for ev in state["events"]]

    # ------------------------------ internals ----------------------------- #

    def _state(self, key: str) -> _SeriesState:
        st = self._series.get(key)
        if st is None:
            st = self._series[key] = _SeriesState(self.config.window)
        return st

    def _emit(
        self,
        events: List[HealthEvent],
        detector: str,
        spec: Dict[str, Any],
        key: str,
        value: float,
        step: int,
        phase: Optional[int],
        message: str,
        st: _SeriesState,
        **extra: Any,
    ) -> None:
        self._quiet[(detector, key)] = (
            self._observations + int(self.config.cooldown)
        )
        ev = HealthEvent(
            detector=detector,
            severity=spec["severity"],
            series=key,
            value=value,
            step=step,
            phase=phase,
            message=message,
            fingerprint=self.fingerprint,
            window=[round(v, 6) for v in st.window],
            **extra,
        )
        events.append(ev)
        self.events.append(ev)
        if len(self.events) > self.config.max_events:
            del self.events[: len(self.events) - self.config.max_events]
        self.event_counts[detector] = self.event_counts.get(detector, 0) + 1

    def _evaluate(
        self,
        events: List[HealthEvent],
        detector: str,
        spec: Dict[str, Any],
        key: str,
        value: float,
        step: int,
        phase: Optional[int],
    ) -> None:
        st = self._state(key)
        kind = spec["kind"]
        warm = st.count >= int(self.config.warmup)
        cooled = self._observations >= self._quiet.get((detector, key), -1)
        if not cooled:
            return
        if kind == "zscore" and warm:
            baseline = st.mean
            zmax = float(spec["zmax"])
            min_abs = float(spec["min_abs"])
            # std floor: a dead-flat series (std ~ 0) would make any
            # nonzero delta an infinite z; floor by a fraction of the
            # baseline magnitude plus an absolute epsilon
            std = max(st.std(), 0.05 * abs(baseline), 1e-8)
            z = (value - baseline) / std
            if z > zmax and value > min_abs:
                self._emit(
                    events, detector, spec, key, value, step, phase,
                    f"{key} = {value:.4g} is {z:.1f} sigma above its "
                    f"EWMA {baseline:.4g} (zmax {spec['zmax']})",
                    st, zscore=round(z, 2), baseline=baseline,
                )
        elif kind == "collapse" and warm:
            baseline = st.mean
            bound = float(spec["frac"]) * baseline
            min_baseline = float(spec["min_baseline"])
            if baseline > min_baseline and value < bound:
                self._emit(
                    events, detector, spec, key, value, step, phase,
                    f"{key} = {value:.4g} collapsed below "
                    f"{spec['frac']} x its EWMA {baseline:.4g}",
                    st, baseline=baseline, threshold=bound,
                )
        elif kind == "above":
            threshold = float(spec["threshold"])
            above = value > threshold
            if above:
                self._emit(
                    events, detector, spec, key, value, step, phase,
                    f"{key} = {value:.4g} exceeds the absolute bound "
                    f"{threshold:.4g}",
                    st, threshold=threshold,
                )
        elif kind == "flatline":
            eps = float(spec["eps"])
            patience = int(spec["patience"])
            if abs(value) < eps:
                st.flat_run += 1
            else:
                st.flat_run = 0
            if warm and st.flat_run >= patience:
                self._emit(
                    events, detector, spec, key, value, step, phase,
                    f"{key} has been < {spec['eps']:g} for "
                    f"{st.flat_run} consecutive rows — the signal "
                    f"saturated (no gradient information left in it)",
                    st, threshold=float(spec["eps"]),
                )
                st.flat_run = 0

    # -------------------------------- API --------------------------------- #

    def observe(
        self,
        row: Dict[str, Any],
        step: Optional[int] = None,
        phase: Optional[int] = None,
    ) -> List[HealthEvent]:
        """Feed one host-side stats row; returns the events it tripped.

        ``step`` defaults to an internal observation counter so callers
        without a loop counter (bench, the perf/smoke harnesses) still
        get ordered events. Device arrays in the row are skipped, never
        fetched."""
        if not row:
            return []
        if step is None:
            step = self._observations
        values: Dict[str, float] = {}
        for key, raw in row.items():
            v = _host_float(raw)
            if v is not None:
                values[key] = v
        if not values:
            return []
        events: List[HealthEvent] = []

        # nonfinite precursor first: a NaN would poison the EWMAs below
        nonfinite = self._specs.get("nan-precursor")
        huge = float(nonfinite["huge"]) if nonfinite is not None else 0.0
        for key in list(values):
            v = values[key]
            if not math.isfinite(v):
                # prefix-scoped like the huge branch (a bookkeeping
                # stat outside the watch list must not abort a run),
                # with the same cooldown as every other rule: a
                # persistently-NaN key is one anomaly, not one event
                # per row
                if (
                    nonfinite is not None
                    and key.startswith(NAN_WATCH_PREFIXES)
                    and self._observations
                    >= self._quiet.get(("nan-precursor", key), -1)
                ):
                    self._emit(
                        events, "nan-precursor", nonfinite, key, v, step,
                        phase, f"{key} went non-finite ({v})",
                        self._state(key),
                    )
                del values[key]  # keep the EWMA state finite
            elif (
                nonfinite is not None
                and key.startswith(NAN_WATCH_PREFIXES)
                and abs(v) > huge
            ):
                if (
                    self._observations
                    >= self._quiet.get(("nan-precursor", key), -1)
                ):
                    self._emit(
                        events, "nan-precursor", nonfinite, key, v, step,
                        phase,
                        f"|{key}| = {abs(v):.3g} exceeds "
                        f"{nonfinite['huge']:.0g} — overflow precursor",
                        self._state(key),
                    )
                # an overflow-magnitude sample would poison the EWMA
                # baseline (one 2e8 entropy row makes the NEXT normal
                # row a spurious collapse) — keep it out of the state,
                # like the non-finite branch
                del values[key]

        # evaluate every detector against every candidate series present
        # (pre-update stats = the baseline the new value is judged by);
        # prefix-series detectors (slo-breach) match dynamically-named
        # keys like serve/slo_queue_wait_ratio[tenant=...]
        for did, spec in self._specs.items():
            if spec["kind"] == "nonfinite":
                continue
            candidates = [k for k in spec["series"] if k in values]
            for prefix in spec.get("series_prefix", ()):
                candidates.extend(
                    k for k in sorted(values)
                    if k.startswith(prefix) and k not in candidates
                )
            for key in candidates:
                self._evaluate(
                    events, did, spec, key, values[key], step, phase
                )

        # then advance each series exactly once
        for key, v in values.items():
            self._state(key).update(v, self._alpha)
        self.latest.update(values)
        self._observations += 1
        return events

    def state_summary(self) -> Dict[str, Dict[str, float]]:
        """Per-series EWMA snapshot for the flight recorder."""
        out: Dict[str, Dict[str, float]] = {}
        for key, st in sorted(self._series.items()):
            out[key] = {
                "count": float(st.count),
                "ewma": round(st.mean, 6),
                "std": round(st.std(), 6),
                "last": round(st.window[-1], 6) if st.window else 0.0,
            }
        return out

    def recent_events(self, phase: Optional[int] = None) -> List[HealthEvent]:
        if phase is None:
            return list(self.events)
        return [ev for ev in self.events if ev.phase == phase]

    def health_summary(self) -> Dict[str, float]:
        """Latest value of every ``health/`` series (bench payload)."""
        return {
            k: round(v, 6)
            for k, v in sorted(self.latest.items())
            if k.startswith("health/")
        }


def detector_defaults_table() -> List[Tuple[str, str, str, str]]:
    """(id, kind, severity, params) rows — docs/CLI rendering helper."""
    rows = []
    for did, spec in sorted(DEFAULT_DETECTORS.items()):
        params = ", ".join(
            f"{k}={v}" for k, v in sorted(spec.items())
            if k not in ("series", "series_prefix", "kind", "severity")
        )
        rows.append((did, spec["kind"], spec["severity"], params))
    return rows


def format_events(events: Sequence[HealthEvent]) -> str:
    lines = []
    for ev in events:
        lines.append(
            f"[{ev.severity}] {ev.detector} @ step {ev.step}"
            f"{'' if ev.phase is None else f' phase {ev.phase}'}: "
            f"{ev.message}"
        )
    return "\n".join(lines)
