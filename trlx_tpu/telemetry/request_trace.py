"""Per-request distributed tracing for the serving tier.

The serving histograms (``serve/*``, docs/observability.md "Serving
metrics") answer "how is the tier doing"; they cannot answer the
operational question a multi-tenant tier exists for: *where did THIS
request's latency go, and which stage is the tail made of?* This module
is the request-granular half: a ``trace_id`` minted at
``InferenceServer.submit`` rides the typed
:class:`~trlx_tpu.serving.scheduler.Request` through the scheduler
queue, quota admission, the prefix-cache plan, engine prefill, decode,
and harvest — and at delivery the whole lifecycle is emitted as one
parented span chain into the process-global span tracer, exported into
the **same** Perfetto JSONL as the phase spans and counter tracks.

The chain is built *retrospectively*: the stages of one request
interleave with every other request's on the single serving thread, so
they can never be context managers on the tracer's thread stack.
Instead each layer stamps host marks on the shared telemetry clock
(scheduler: quota-block/pick; engine: admit/first-token/done/harvest;
streaming: first-push/close) and :func:`emit_request_trace` turns the
marks into explicitly-stamped spans recorded via
:meth:`~trlx_tpu.telemetry.tracer.Tracer.record`. Each request renders
as its own Perfetto track, named by tenant (synthetic tids above
:data:`REQUEST_TRACK_BASE` keep them clear of real thread ids).

Critical-path contract (what ``--trace-report`` relies on): the spans
named in :data:`STAGES` are **disjoint and contiguous** — clamped onto
the mark chain submitted ≤ quota-block ≤ picked ≤ admitted ≤
first-token ≤ done ≤ completed ≤ delivered — so per request they sum to
the root ``serve/request`` duration exactly, and to the request's
``serve/e2e_ms`` histogram observation up to the (host-trivial)
delivery stage. Overlay spans (``serve/prefix_plan``, ``serve/stream``,
``serve/decode_segment``) carry extra structure and are *excluded* from
the sum.

Cost model: everything here is host-side bookkeeping; the jitted
programs never change. With the tracer disabled the serving layer skips
mark collection and emission entirely — the per-span cost stays the
shared ``NULL_SPAN`` contract of the tracer.
"""

from __future__ import annotations

import itertools
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from trlx_tpu.telemetry.tracer import Span, Tracer

#: synthetic-tid floor for per-request Perfetto tracks: far above any
#: real thread id, so request tracks never collide with the serving
#: thread's own span track
REQUEST_TRACK_BASE = 1 << 22

#: root span of every request trace
ROOT = "serve/request"

#: the disjoint critical-path stages (in lifecycle order); per request
#: their durations sum to the root span's — the ``--trace-report``
#: decomposition invariant
STAGES = (
    "serve/queue",
    "serve/quota_hold",
    "serve/prefill",
    "serve/decode",
    "serve/harvest_wait",
    "serve/deliver",
)

#: overlay spans: extra structure, excluded from the critical-path sum
OVERLAYS = ("serve/prefix_plan", "serve/stream", "serve/decode_segment")


#: process-wide mint counter: request_ids are per-server (each
#: InferenceServer counts from 0), so two servers in one process would
#: otherwise mint colliding ids — and the analyzer groups by trace_id,
#: merging the collided chains into one corrupted view
_mint_seq = itertools.count()


def mint_trace_id(request_id: int) -> str:
    """A globally unique trace id for one serving request: the pid keeps
    ids distinct when several serving processes append into one span
    log, the process-wide mint sequence keeps them distinct across
    servers within a process, and the (per-server) request id keeps the
    id humanly correlatable with the request it traces."""
    return f"req-{os.getpid():x}-{next(_mint_seq):x}-{int(request_id):x}"


def request_track(request_id: int, tenant: str) -> Tuple[int, str]:
    """(synthetic tid, track name) for a request's Perfetto track —
    one track per request, *named by tenant* so Perfetto groups a
    tenant's requests visually."""
    return REQUEST_TRACK_BASE + int(request_id), f"tenant:{tenant}"


def _stamp(
    name: str,
    start: float,
    end: float,
    tid: int,
    tname: str,
    attrs: Dict[str, Any],
) -> Span:
    span = Span(name, attrs)
    span.start = start
    span.end = max(start, end)
    span.thread_id = tid
    span.thread_name = tname
    return span


def emit_request_trace(
    tracer: Tracer,
    *,
    trace_id: str,
    request_id: int,
    tenant: str,
    priority: int,
    slo_class: str,
    streamed: bool,
    tokens: int,
    marks: Dict[str, float],
    timing: Dict[str, float],
    delivered: float,
    status: str = "ok",
    quota_blocked_at: Optional[float] = None,
    picked_at: Optional[float] = None,
    step_times: Optional[Sequence[float]] = None,
    step_epochs: Optional[Sequence[int]] = None,
    plan_window: Optional[Tuple[float, float]] = None,
    stream_window: Optional[Tuple[float, float]] = None,
) -> Optional[int]:
    """Record one completed request's span chain; returns the root
    span's index (``None`` when the tracer is disabled).

    ``marks`` is the engine's raw mark dict
    (:meth:`~trlx_tpu.inference.engine.ContinuousBatchingEngine.
    pop_request_record`); ``timing`` its ms decomposition (the same
    values the ``serve/*`` histograms observed, carried as root attrs
    so tests and the analyzer can tie the chain to the histogram
    observation without joining streams). ``status`` is ``"ok"`` or
    ``"abandoned"`` (the request was ``pop_result``-ed mid-flight; its
    row still decoded to harvest, and the chain still closes —
    trace completeness covers every *completed row*, not just every
    claimed result)."""
    if not tracer.enabled:
        return None
    submitted = float(marks["submitted"])
    completed = float(marks["completed"])
    # clamp the chain monotone: every mark is a host stamp from a
    # different layer; a sub-ms inversion (e.g. a pick and an admit in
    # the same pump iteration) must not produce a negative stage
    blocked = quota_blocked_at
    picked = picked_at if picked_at else None
    admitted = max(submitted, float(marks.get("admitted", submitted)))
    if blocked is not None:
        blocked = min(max(float(blocked), submitted), admitted)
        picked = min(max(float(picked or blocked), blocked), admitted)
    first = max(admitted, float(marks.get("first_token", admitted)))
    done = min(max(float(marks.get("done", completed)), first), completed)
    completed = max(completed, first)
    delivered = max(float(delivered), completed)

    tid, tname = request_track(request_id, tenant)
    base = {"trace_id": trace_id, "tenant": tenant}
    root = _stamp(
        ROOT,
        submitted,
        delivered,
        tid,
        tname,
        dict(
            base,
            request_id=int(request_id),
            priority=int(priority),
            slo_class=slo_class,
            stream=bool(streamed),
            tokens=int(tokens),
            status=status,
            **{k: round(float(v), 3) for k, v in timing.items()},
        ),
    )
    if status != "ok":
        root.status = status
    root_ix = tracer.record(root)
    if root_ix is None:
        return None

    def child(name, start, end, parent=None, depth=1, **attrs):
        span = _stamp(name, start, end, tid, tname, dict(base, **attrs))
        span.depth = depth
        if attrs.get("status", "ok") != "ok":
            # the chrome exporter writes args["status"] from the SPAN
            # field — an attr alone would export as "ok"
            span.status = attrs["status"]
        return tracer.record(
            span, parent=root_ix if parent is None else parent
        )

    # --- the disjoint critical-path stages --------------------------- #
    if blocked is not None:
        child("serve/queue", submitted, blocked)
        child("serve/quota_hold", blocked, picked)
        child("serve/queue", picked, admitted, leg="post-quota")
    else:
        child("serve/queue", submitted, admitted)
    prefill_attrs: Dict[str, Any] = {}
    chunk_offs = marks.get("prefill_chunk_offsets")
    if chunk_offs:
        # chunked prefill (rollout.prefill_chunk): one entry per
        # dispatched chunk window — the prompt-column offset it started
        # at and its dispatch wall relative to admission, so
        # --trace-report can attribute a chunked admission's spread
        # across pump iterations (stall-free admission evidence)
        prefill_attrs["chunks"] = len(chunk_offs)
        prefill_attrs["chunk_cols"] = [int(c["col"]) for c in chunk_offs]
        prefill_attrs["chunk_offsets_ms"] = [
            float(c["ms"]) for c in chunk_offs
        ]
    child("serve/prefill", admitted, first, **prefill_attrs)
    decode_attrs: Dict[str, Any] = {"tokens": int(tokens)}
    if marks.get("spec_segments"):
        # speculative verify steps committed > 1 token each: the
        # cadence estimator must not read tokens > steps (or the wider
        # per-step walls) as host bubbles
        decode_attrs["spec_segments"] = int(marks["spec_segments"])
        decode_attrs["accepted"] = int(marks.get("spec_accepted", 0))
    offsets: List[float] = []
    if step_times:
        offsets = [
            round(max(0.0, (t - first)) * 1000.0, 3) for t in step_times
        ]
        decode_attrs["steps"] = len(offsets)
        decode_attrs["step_offsets_ms"] = offsets
    decode_ix = child("serve/decode", first, done, **decode_attrs)
    child("serve/harvest_wait", done, completed)
    child("serve/deliver", completed, delivered, status=status)

    # --- overlays ---------------------------------------------------- #
    if plan_window is not None:
        child("serve/prefix_plan", plan_window[0], plan_window[1])
    if streamed and stream_window is not None:
        child("serve/stream", stream_window[0], stream_window[1])
    if step_times and step_epochs and decode_ix is not None:
        # decode segments: maximal runs of this row's decode steps with
        # no interleaved admission prefill (epoch constant). Segment
        # boundaries are where the host loop left decode to admit —
        # the admission-bubble structure, visible on the timeline.
        seg_start = first
        run_start = 0
        for i in range(1, len(step_times) + 1):
            if i == len(step_times) or step_epochs[i] != step_epochs[run_start]:
                child(
                    "serve/decode_segment",
                    seg_start,
                    float(step_times[i - 1]),
                    parent=decode_ix,
                    depth=2,
                    seg=step_epochs[run_start],
                    steps=i - run_start,
                )
                seg_start = float(step_times[i - 1])
                run_start = i
    return root_ix
