"""Device-memory metrics: measured HBM next to the static predictions.

Engine 7 (``analysis/resource_audit.py``) predicts peak live HBM per
device *statically*; nothing reported what the allocator actually did,
so a static-vs-measured gap (fragmentation, un-donated buffers XLA kept,
runtime scratch) was invisible. This module reads
``device.memory_stats()`` — the PJRT allocator counters TPUs expose
(``bytes_in_use`` / ``peak_bytes_in_use`` / transfer counters where the
runtime provides them) — and turns the gap into a printed attribution.

Everything degrades to empty dicts on backends without the counters
(CPU returns ``None``), so callers log unconditionally and the keys
simply vanish on unsupported hardware.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

# allocator counters we surface when present; anything absent is skipped
_GAUGE_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_reserved",
               "largest_alloc_size", "bytes_limit",
               "bytes_reservable_limit")
# monotonically-increasing counters (per-phase deltas are meaningful)
_COUNTER_KEYS = ("num_allocs",
                 "bytes_transferred_to_device",
                 "bytes_transferred_from_device")


def device_memory_stats() -> List[Dict[str, int]]:
    """Raw ``memory_stats()`` per local device; ``[]`` when the backend
    has no allocator counters (CPU) or the API raises."""
    import jax

    out: List[Dict[str, int]] = []
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            return []
        if not stats:
            return []
        out.append({k: int(v) for k, v in stats.items()
                    if isinstance(v, (int, float))})
    return out


def snapshot() -> Dict[str, int]:
    """Aggregated allocator gauges across local devices: the max per
    gauge (the binding device) and the sum per transfer counter."""
    per_device = device_memory_stats()
    if not per_device:
        return {}
    agg: Dict[str, int] = {}
    for key in _GAUGE_KEYS:
        vals = [s[key] for s in per_device if key in s]
        if vals:
            agg[key] = max(vals)
    for key in _COUNTER_KEYS:
        vals = [s[key] for s in per_device if key in s]
        if vals:
            agg[key] = sum(vals)
    return agg


def phase_memory_stats(prefix: str = "mem/") -> Dict[str, float]:
    """Loggable per-phase memory stats (empty on CPU): live/peak HBM of
    the most-loaded device plus any transfer-byte counters — logged next
    to the per-phase span durations so bytes and milliseconds share a
    row."""
    agg = snapshot()
    out: Dict[str, float] = {}
    if "bytes_in_use" in agg:
        out[f"{prefix}hbm_live_bytes"] = float(agg["bytes_in_use"])
    if "peak_bytes_in_use" in agg:
        out[f"{prefix}hbm_peak_bytes"] = float(agg["peak_bytes_in_use"])
    for key in ("bytes_transferred_to_device", "bytes_transferred_from_device"):
        if key in agg:
            out[f"{prefix}{key}"] = float(agg[key])
    return out


def static_vs_measured(
    trainer=None,
    kind: str = "ppo",
    static_peak_bytes: Optional[int] = None,
) -> Dict[str, Any]:
    """The printed attribution: engine-7's static peak-HBM prediction
    for the train step next to the allocator's measured peak.

    Semantics matter here: ``peak_bytes_in_use`` is the PROCESS-lifetime
    high-water mark — it covers the sampler (KV caches), the behavior
    snapshot, the stream store, and the train step together, while the
    static number bounds the train step alone. The ratio is therefore a
    *phase-footprint over step-contract* measure (≥ 1 by construction on
    a real run), not pure allocator overhead: a growing ratio across
    rounds means the run's memory grew somewhere the step lockfile does
    not gate (decode/KV, snapshot, store, or genuine allocator
    fragmentation/scratch) — the signal to go look, not the diagnosis.

    ``static_peak_bytes`` skips the (seconds-long at real shapes)
    re-trace when the caller already holds engine-7's number — bench
    computes it once and passes it in."""
    out: Dict[str, Any] = {}
    if static_peak_bytes is not None:
        out["static_peak_hbm_bytes"] = int(static_peak_bytes)
    elif trainer is not None:
        from trlx_tpu.analysis.resource_audit import trainer_step_resources

        try:
            res = trainer_step_resources(trainer, kind=kind)
            out["static_peak_hbm_bytes"] = int(res.peak_hbm_bytes)
        except Exception as e:  # measured numbers must still report
            out["static_resource_error"] = f"{type(e).__name__}: {e}"
    agg = snapshot()
    measured: Optional[int] = agg.get("peak_bytes_in_use")
    if measured is not None:
        out["measured_peak_hbm_bytes"] = int(measured)
        static = out.get("static_peak_hbm_bytes")
        if static:
            out["measured_process_peak_over_static_step"] = round(
                measured / static, 2
            )
    return out
