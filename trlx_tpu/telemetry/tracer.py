"""Structured span tracer: one monotonic clock for the whole repo.

The phase loop used to time itself with scattered ``time.time()`` calls
(``drain_ms`` / ``residual_ms`` stopwatches in the trainer, a ``Clock``
in the orchestrator, a third stopwatch in ``Logger``) — three clocks, no
nesting, nothing machine-readable. A :class:`Span` is the replacement:
a context manager stamped from ONE monotonic clock (:func:`monotonic`),
nested via a per-thread stack, exception-safe (the span closes with
``status="error"`` and re-raises), and recorded into a bounded ring the
perf auditor / bench / Perfetto exporter all read.

Cost model, because spans sit on the collect critical path:

- **enabled** (default on rank 0): two ``time.monotonic()`` calls, one
  list push/pop, one deque append per span — no device work, no syncs.
  Any ``block_until_ready`` fence belongs to the *instrumented code*,
  never to the tracer; spans are placed only at boundaries that already
  synchronize (drain, residual scan, phase end).
- **disabled**: :func:`Tracer.span` returns the shared :data:`NULL_SPAN`
  singleton — one attribute read and a call, nothing allocated.
- **forced** (``force=True``): measured even when the tracer is
  disabled (so span durations can be the single source of truth for
  always-on stats like ``exp/overlap_drain_ms``) but recorded only when
  enabled. Use it for the handful of phase-boundary spans whose
  durations feed reported stats; never in per-token loops.

Module is stdlib-only at import time so low-level utilities
(``trlx_tpu.utils``) can source their clock from here without cycles.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

#: The single monotonic clock (seconds). Every reported duration in the
#: repo — Clock, Logger, spans, the perf lockfile — derives from this.
monotonic: Callable[[], float] = time.monotonic

#: default span-ring capacity; override per process with the
#: ``TRLX_TELEMETRY_RING`` env var or ``train.telemetry.ring_size``
#: (per-request serving spans multiply span volume — docs/observability.md)
DEFAULT_RING_SIZE = 65536


def env_ring_size() -> int:
    """The span-ring capacity the environment asks for
    (``TRLX_TELEMETRY_RING``), falling back to :data:`DEFAULT_RING_SIZE`.
    A malformed value falls back too — a typo must not kill the run that
    was trying to observe itself."""
    raw = os.environ.get("TRLX_TELEMETRY_RING", "")
    try:
        n = int(raw)
    except ValueError:
        return DEFAULT_RING_SIZE
    return n if n > 0 else DEFAULT_RING_SIZE


class _NullSpan:
    """Shared no-op span returned while the tracer is disabled."""

    __slots__ = ()

    name = ""
    status = "ok"
    start = 0.0
    end = 0.0
    depth = 0
    parent = None
    index = -1
    duration_ms = 0.0

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> None:
        pass


NULL_SPAN = _NullSpan()


class Span:
    """One timed region. Use as a context manager:

    ``with tracer.span("phase/collect", rollouts=128) as sp: ...``

    After exit, ``sp.duration_ms`` is the measured wall-clock and
    ``sp.status`` is ``"error"`` if the body raised (the exception
    propagates — a span never swallows)."""

    __slots__ = (
        "name", "attrs", "start", "end", "status",
        "index", "parent", "depth", "thread_id", "thread_name", "_tracer",
    )

    def __init__(
        self,
        name: str,
        attrs: Optional[Dict[str, Any]] = None,
        tracer: Optional["Tracer"] = None,
    ):
        self.name = name
        self.attrs: Dict[str, Any] = attrs or {}
        self.start = 0.0
        self.end = 0.0
        self.status = "ok"
        self.index = -1
        self.parent: Optional[int] = None
        self.depth = 0
        self.thread_id = 0
        self.thread_name = ""
        self._tracer = tracer  # None: forced-but-unrecorded span

    @property
    def duration_ms(self) -> float:
        return max(0.0, (self.end - self.start) * 1000.0)

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        if self._tracer is not None:
            self._tracer._open(self)
        self.start = monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.end = monotonic()
        if exc_type is not None:
            self.status = "error"
        if self._tracer is not None:
            self._tracer._close(self)
        return False  # never swallow

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "start_s": self.start,
            "duration_ms": self.duration_ms,
            "depth": self.depth,
            "index": self.index,
            "parent": self.parent,
            "status": self.status,
            "attrs": dict(self.attrs),
        }


class Tracer:
    """Thread-safe span recorder with a bounded ring buffer.

    The per-thread span stack gives nesting (parent/depth) for free on
    whatever thread opens the span; completed spans land in one shared
    deque (``maxlen`` drops the oldest — ``dropped`` counts them so a
    truncated trace is visible, never silent)."""

    def __init__(
        self, enabled: bool = True, max_records: int = DEFAULT_RING_SIZE
    ):
        self.enabled = enabled
        self.dropped = 0
        self._records: "deque[Span]" = deque(maxlen=max_records)
        self._local = threading.local()
        self._lock = threading.Lock()
        self._next_index = 0

    # ------------------------------- API -------------------------------- #

    def span(self, name: str, force: bool = False, **attrs):
        """A new span (or :data:`NULL_SPAN` when disabled and not
        forced). ``force=True`` spans measure time regardless of the
        enabled flag but are only *recorded* when enabled."""
        if not self.enabled:
            return Span(name, attrs or None, None) if force else NULL_SPAN
        return Span(name, attrs or None, self)

    def record(self, span: Span, parent: Optional[int] = None) -> Optional[int]:
        """Record an externally-stamped span — explicit ``start``/``end``
        already set by the caller, never touching the per-thread stack.

        The per-request serving traces (telemetry/request_trace.py) are
        built retrospectively at harvest, long after each stage actually
        ran, so they cannot be context managers: the caller stamps start/
        end/thread fields and links parents by recorded index (``parent``
        overrides any pre-set ``span.parent``). Returns the assigned
        index, or ``None`` when the tracer is disabled (nothing recorded
        — the disabled-mode cost contract)."""
        if not self.enabled:
            return None
        if parent is not None:
            span.parent = parent
        with self._lock:
            span.index = self._next_index
            self._next_index += 1
            if len(self._records) == self._records.maxlen:
                self.dropped += 1
            self._records.append(span)
        return span.index

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self.dropped = 0
            self._next_index = 0

    def set_max_records(self, max_records: int) -> None:
        """Resize the ring, keeping the newest records; evictions a
        shrink forces are counted in ``dropped`` like any other."""
        with self._lock:
            evicted = max(0, len(self._records) - int(max_records))
            self._records = deque(self._records, maxlen=int(max_records))
            self.dropped += evicted

    def spans(self, name: Optional[str] = None) -> List[Span]:
        """Completed spans in close order (optionally filtered by name)."""
        with self._lock:
            records = list(self._records)
        if name is not None:
            records = [s for s in records if s.name == name]
        return records

    def last(self, name: str) -> Optional[Span]:
        with self._lock:
            for s in reversed(self._records):
                if s.name == name:
                    return s
        return None

    def ancestors(self, span: Span) -> List[Span]:
        """Enclosing spans of ``span``, innermost first (resolved via
        recorded indices — parents close after children, so by the time
        a tree is inspected the whole chain is in the ring)."""
        by_index = {s.index: s for s in self.spans()}
        out: List[Span] = []
        parent = span.parent
        while parent is not None and parent in by_index:
            s = by_index[parent]
            out.append(s)
            parent = s.parent
        return out

    def stats(self, prefix: str = "") -> Dict[str, Dict[str, float]]:
        """Per-span-name aggregates: count, p50/p95/max/total ms.

        Percentiles use nearest-rank on the closed spans — the perf
        lockfile gates p50 (jitter-robust) and records p95 for tails."""
        groups: Dict[str, List[float]] = {}
        for s in self.spans():
            if prefix and not s.name.startswith(prefix):
                continue
            groups.setdefault(s.name, []).append(s.duration_ms)
        out: Dict[str, Dict[str, float]] = {}
        for name, durs in sorted(groups.items()):
            durs.sort()
            out[name] = {
                "count": float(len(durs)),
                "p50_ms": quantile(durs, 0.5),
                "p95_ms": quantile(durs, 0.95),
                "max_ms": durs[-1],
                "total_ms": sum(durs),
            }
        return out

    # ----------------------------- internal ----------------------------- #

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _open(self, span: Span) -> None:
        stack = self._stack()
        with self._lock:
            span.index = self._next_index
            self._next_index += 1
        span.parent = stack[-1].index if stack else None
        span.depth = len(stack)
        thread = threading.current_thread()
        span.thread_id = thread.ident or 0
        span.thread_name = thread.name
        stack.append(span)

    def _close(self, span: Span) -> None:
        stack = self._stack()
        # exception-tolerant pop: an abandoned inner span (a generator
        # that never resumed, say) must not wedge the stack forever
        while stack:
            top = stack.pop()
            if top is span:
                break
        with self._lock:
            if len(self._records) == self._records.maxlen:
                self.dropped += 1
            self._records.append(span)


def quantile(sorted_durs: Sequence[float], q: float) -> float:
    """Nearest-rank quantile of an ascending-sorted sequence."""
    if not sorted_durs:
        return 0.0
    ix = min(len(sorted_durs) - 1, max(0, int(round(q * (len(sorted_durs) - 1)))))
    return sorted_durs[ix]


# --------------------------- Perfetto / chrome --------------------------- #

def chrome_trace_events(spans: Iterable[Span]) -> List[Dict[str, Any]]:
    """Spans as chrome-tracing "complete" (``ph: X``) events: ``ts`` /
    ``dur`` in microseconds on the shared monotonic timebase, ``tid`` =
    the opening thread, span attrs + status under ``args``.

    Prepends chrome ``metadata`` (``ph: M``) name events — one
    ``process_name`` plus a ``thread_name`` per distinct tid — so
    Perfetto/chrome:tracing label the tracks with real thread names
    (main loop vs the background writer) instead of bare integer tids."""
    pid = os.getpid()
    complete = []
    tid_names: Dict[int, str] = {}
    for s in spans:
        name = getattr(s, "thread_name", "") or f"tid-{s.thread_id}"
        tid_names.setdefault(s.thread_id, name)
        complete.append(
            {
                "name": s.name,
                "ph": "X",
                "ts": round(s.start * 1e6, 3),
                "dur": round((s.end - s.start) * 1e6, 3),
                "pid": pid,
                "tid": s.thread_id,
                "args": {**s.attrs, "status": s.status, "depth": s.depth},
            }
        )
    if not complete:
        return []
    meta: List[Dict[str, Any]] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "args": {"name": "trlx_tpu"},
        }
    ]
    for tid, name in sorted(tid_names.items()):
        meta.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": name},
            }
        )
    return meta + complete


def chrome_counter_events(
    series: Dict[str, Sequence[tuple]],
) -> List[Dict[str, Any]]:
    """Gauge timeseries as chrome-tracing counter-track (``ph: C``)
    events, so memory/occupancy ride alongside the span tracks in one
    Perfetto timeline: ``series`` maps a counter name (``mem/hbm_live``,
    ``engine/slot_util``) to ``(t, value)`` samples on the shared
    monotonic timebase — exactly what
    :meth:`~trlx_tpu.telemetry.metrics.MetricsRegistry.gauge_series`
    returns. Perfetto draws each name as its own stepped area chart."""
    pid = os.getpid()
    events: List[Dict[str, Any]] = []
    for name in sorted(series):
        for t, value in series[name]:
            events.append(
                {
                    "name": name,
                    "ph": "C",
                    "ts": round(t * 1e6, 3),
                    "pid": pid,
                    "args": {"value": value},
                }
            )
    return events


def export_chrome_jsonl(
    path: str,
    spans: Iterable[Span],
    writer=None,
    counters: Optional[Dict[str, Sequence[tuple]]] = None,
) -> int:
    """Append the span stream to ``path`` as JSONL (one trace event per
    line). Returns the number of events written.

    Pass a caller-owned ``BackgroundJSONLWriter`` (``utils/
    async_writer.py``) to queue the write off your critical path — you
    own its flush/close cadence, exactly as rollout logging does. With
    no writer the write is plain synchronous file I/O (spawning a
    thread just to join it would be the same blocking with extra cost)
    — fine for end-of-run exports, not for per-phase hot paths. Load
    in Perfetto/chrome via :func:`chrome_trace_from_jsonl` (the array
    wrapper).

    ``counters`` adds counter-track events (gauge timeseries — see
    :func:`chrome_counter_events`) to the same file; they share the
    span events' timebase, so a ``mem/hbm_live`` step lines up under
    the phase span that caused it."""
    events = chrome_trace_events(spans)
    if counters:
        events += chrome_counter_events(counters)
    if not events:
        return 0
    if writer is not None:
        writer.submit(path, events)
        return len(events)
    with open(path, "a", encoding="utf-8") as fh:
        fh.write("\n".join(json.dumps(e) for e in events) + "\n")
    return len(events)


def chrome_trace_from_jsonl(jsonl_path: str, out_path: str) -> int:
    """Wrap a span JSONL stream into the JSON-array file
    chrome://tracing and ui.perfetto.dev load directly."""
    events = []
    with open(jsonl_path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump({"traceEvents": events}, fh)
    return len(events)
