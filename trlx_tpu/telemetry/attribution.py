"""Goodput & utilization attribution: statics ÷ span times.

The measurement layer already holds both halves of "where did the time
go": engine 7 (``analysis/resource_audit.py``) counts each traced
program's exact matmul FLOPs and boundary bytes *statically*, and the
span tracer measures what every phase region actually took. Nothing
joined them — train MFU 0.299 / collect 0.157 were whole-phase numbers
hand-derived in bench.py, with no per-program breakdown and no
accounting of the async schedule's bubbles. This module is the join:

- :func:`attribute` — for each (traced program, span) pair in a work
  map, ``measured utilization = static work × fires ÷ (span wall ×
  device peak)``: measured MFU against the chip's published bf16 peak
  and HBM-BW utilization against its published bandwidth, where the
  byte side is the program's boundary traffic floor (sharded input
  bytes + output bytes — the program must at least read its inputs and
  write its outputs; fused internals are uncounted, so the utilization
  is a lower bound exactly like bench's roofline denominators).
- :func:`bubble_breakdown` — the async schedule's idle attribution
  (learner drain, version-lag guard hold, admission bookkeeping,
  learner idle) as per-phase milliseconds and fractions of the phase
  wall — the LlamaRL-style table that justifies (or indicts) an async
  design choice.
- :func:`phase_goodput` — trained samples per second of *total* phase
  wall (collect + train + eval + checkpoint spans), the end-to-end
  number utilization percentages tend to flatter.

Device peaks are the published per-chip specs (moved here from bench.py
so bench and the attribution table can never disagree); backends
without a published spec (CPU) fall back to a documented *nominal*
entry so the table stays populated — those utilizations are only
meaningful round-over-round on the same host, never against hardware.

Everything here is host-side arithmetic over dicts the caller already
holds; nothing traces, compiles, or touches devices except
:func:`trainer_program_resources`, which re-traces (tracing only, no
compilation — the engine-7 pattern bench already pays for the train
step) a LIVE trainer's programs at the real workload shape.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

# Published bf16 peak per chip by device_kind (dense, no sparsity) —
# the single source bench.py imports.
BF16_PEAK_TFLOPS = {
    "TPU v3": 123.0,
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,  # v5e
    "TPU v5": 459.0,  # v5p
    "TPU v6 lite": 918.0,  # v6e (Trillium)
}

# Published HBM bandwidth per chip (GB/s).
HBM_PEAK_GBPS = {
    "TPU v3": 900.0,
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,  # v5e
    "TPU v5": 2765.0,  # v5p
    "TPU v6 lite": 1640.0,  # v6e
}

# Nominal fallback peaks for backends with no published spec, so the
# attribution table stays populated on a CPU run. A modern server
# socket lands in this ballpark under XLA:CPU, but the point is
# round-over-round comparability on ONE host, not absolute truth —
# rows priced off these carry ``peak_nominal: true``.
NOMINAL_PEAKS = {
    "cpu": (0.2, 50.0),  # (tflops, GB/s)
}


def device_peaks(device_kind: str) -> Tuple[Optional[float], Optional[float], bool]:
    """(peak_tflops, peak_gbps, nominal?) for a ``device_kind`` string;
    (None, None, False) when neither a published nor a nominal entry
    exists — utilization columns then render empty, honestly."""
    if device_kind in BF16_PEAK_TFLOPS:
        return (
            BF16_PEAK_TFLOPS[device_kind],
            HBM_PEAK_GBPS.get(device_kind),
            False,
        )
    nominal = NOMINAL_PEAKS.get(device_kind.lower())
    if nominal:
        return nominal[0], nominal[1], True
    return None, None, False


# ------------------------------- work maps -------------------------------- #


@dataclass(frozen=True)
class WorkItem:
    """One (traced program, phase window) join. ``span`` is the WINDOW
    whose wall the program's work is charged against — a phase-level
    span containing the sync points, because per-call dispatch spans
    measure host dispatch, not device occupancy (an async jit call
    returns in microseconds while the device grinds). The fire count
    comes from ``count_span`` (a per-call span's count) or
    ``count_key`` (a stats counter, for programs with no per-call span
    like the engine's per-token decode_step); with neither, the window
    span's own count. Rows sharing a window therefore decompose it:
    each row is "what utilization did THIS program's static work
    achieve over the window", and their sum is the window's total."""

    program: str
    span: str
    count_span: str = ""
    count_key: str = ""


#: the fixed-sampler PPO phase: the compiled sampler fires once per
#: chunk (collect/decode spans count them) over the collect window;
#: streamed epoch-1 steps + the residual fused scan (epochs 2..E)
#: charge the train window — under phase overlap their device work
#: partially hides inside collect, and the train window holds the
#: drain that waits for it (a conservative split, documented).
PPO_FIXED_WORK: Tuple[WorkItem, ...] = (
    WorkItem("ppo.rollout", "phase/collect", count_span="collect/decode"),
    WorkItem(
        "ppo.train_step", "phase/train", count_span="train/epoch1_dispatch"
    ),
    WorkItem("ppo.train_phase", "phase/train", count_span="train/residual"),
)

#: the continuous engine's three jitted programs decompose the collect
#: window; decode_step has no per-call span (hundreds of fires per
#: phase inside the drive loop), so its count is the
#: ``engine/decode_steps`` stat.
PPO_ENGINE_WORK: Tuple[WorkItem, ...] = (
    WorkItem(
        "ppo.engine_prefill", "phase/collect", count_span="collect/prefill"
    ),
    WorkItem(
        "ppo.engine_decode_step", "phase/collect",
        count_key="engine/decode_steps",
    ),
    WorkItem(
        "ppo.engine_refill", "phase/collect",
        count_span="collect/slot_recycle",
    ),
    WorkItem(
        "ppo.train_step", "phase/train", count_span="train/epoch1_dispatch"
    ),
    WorkItem("ppo.train_phase", "phase/train", count_span="train/residual"),
)


def default_work(engine: str = "fixed", kind: str = "ppo") -> Tuple[WorkItem, ...]:
    items = PPO_ENGINE_WORK if engine == "continuous" else PPO_FIXED_WORK
    if kind == "ppo":
        return items
    return tuple(
        WorkItem(
            f"{kind}.{w.program.split('.', 1)[1]}",
            w.span,
            w.count_span,
            w.count_key,
        )
        for w in items
    )


# ------------------------------ attribution ------------------------------- #


@dataclass
class AttributionRow:
    """Measured utilization of one traced program over one span window."""

    program: str
    span: str
    calls: float                    # program executions in the window
    wall_ms: float                  # span total wall covering them
    gflops_per_call: float          # engine-7 static FLOPs / 1e9
    mbytes_per_call: float          # static boundary bytes / 1e6
    achieved_tflops_per_dev: float
    achieved_gbps_per_dev: float
    mfu: Optional[float] = None
    hbm_util: Optional[float] = None
    peak_nominal: bool = False

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "program": self.program,
            "span": self.span,
            "calls": self.calls,
            "wall_ms": round(self.wall_ms, 1),
            "gflops_per_call": round(self.gflops_per_call, 3),
            "mbytes_per_call": round(self.mbytes_per_call, 3),
            "achieved_tflops_per_dev": round(self.achieved_tflops_per_dev, 4),
            "achieved_gbps_per_dev": round(self.achieved_gbps_per_dev, 2),
        }
        if self.mfu is not None:
            out["mfu"] = round(self.mfu, 4)
        if self.hbm_util is not None:
            out["hbm_util"] = round(self.hbm_util, 4)
        if self.peak_nominal:
            out["peak_nominal"] = True
        return out


def _static_bytes(res: Dict[str, Any]) -> float:
    """The program's boundary-traffic floor: sharded input bytes +
    output bytes. ``peak_hbm_bytes`` is residency, not traffic — a
    program can re-read a resident buffer many times — so the floor is
    the only static number honestly chargeable per execution."""
    return float(res.get("input_bytes", 0)) + float(res.get("output_bytes", 0))


def attribute(
    resources: Dict[str, Dict[str, Any]],
    span_stats: Dict[str, Dict[str, float]],
    device_kind: str,
    n_devices: int = 1,
    work: Optional[Sequence[WorkItem]] = None,
    counts: Optional[Dict[str, float]] = None,
) -> List[AttributionRow]:
    """Join static program costs with measured span walls.

    :param resources: engine-7 numbers per subject
        (``ProgramResources.to_dict()`` shape — ``flops``,
        ``input_bytes``, ``output_bytes``).
    :param span_stats: :meth:`Tracer.stats` over the measured window.
    :param counts: flat stats/metrics dict for ``count_key`` joins
        (``engine/decode_steps`` etc.).
    :returns: one row per work item whose program AND span were both
        observed; items missing either side are skipped (a fixed-path
        run simply has no engine rows).

    FLOP statics count whole-program work; under data parallelism each
    device executes ``1/n_devices`` of it, so per-device FLOP rates
    divide by ``n_devices``. The byte side does NOT: engine 7 already
    applied per-device sharding divisors to input bytes (replicated
    inputs count in full on every device, which is correct per-device
    traffic), so dividing again would understate HBM utilization by up
    to ``n_devices``×.
    """
    peak_tf, peak_bw, nominal = device_peaks(device_kind)
    rows: List[AttributionRow] = []
    for item in work or PPO_FIXED_WORK:
        res = resources.get(item.program)
        span = span_stats.get(item.span)
        if not res or not span:
            continue
        if item.count_key:
            calls = float((counts or {}).get(item.count_key, 0.0))
        elif item.count_span:
            calls = float(
                (span_stats.get(item.count_span) or {}).get("count", 0.0)
            )
        else:
            calls = float(span.get("count", 0.0))
        wall_ms = float(span.get("total_ms", 0.0))
        if calls <= 0 or wall_ms <= 0:
            continue
        flops = float(res.get("flops", 0))
        nbytes = _static_bytes(res)
        wall_s = wall_ms / 1000.0
        achieved_tf = flops * calls / wall_s / n_devices / 1e12
        achieved_bw = nbytes * calls / wall_s / 1e9  # bytes are per-device
        rows.append(
            AttributionRow(
                program=item.program,
                span=item.span,
                calls=calls,
                wall_ms=wall_ms,
                gflops_per_call=flops / 1e9,
                mbytes_per_call=nbytes / 1e6,
                achieved_tflops_per_dev=achieved_tf,
                achieved_gbps_per_dev=achieved_bw,
                mfu=achieved_tf / peak_tf if peak_tf else None,
                hbm_util=achieved_bw / peak_bw if peak_bw else None,
                peak_nominal=nominal,
            )
        )
    return rows


# ----------------------------- bubbles/goodput ---------------------------- #

#: phase-wall spans: everything the loop spends a phase on
PHASE_SPANS = ("phase/collect", "phase/train", "phase/eval", "phase/checkpoint")


def phase_wall_ms(
    span_stats: Dict[str, Dict[str, float]], phases: int = 1
) -> float:
    """Per-phase wall: the phase-level spans' total over the measured
    window divided by the phase count."""
    total = sum(
        float(span_stats[name]["total_ms"])
        for name in PHASE_SPANS
        if name in span_stats
    )
    return total / max(1, phases)


def bubble_breakdown(
    span_stats: Dict[str, Dict[str, float]],
    stats: Optional[Dict[str, float]] = None,
    phases: int = 1,
) -> Dict[str, float]:
    """The async schedule's idle attribution, per phase (ms + fraction
    of the phase wall):

    - ``bubble/drain_ms`` — learner waiting for the last rollout chunks
      after the epoch-1 dispatch window closed (``train/drain`` span);
    - ``bubble/guard_hold_ms`` — row-ready minibatches held behind the
      bounded-staleness version-lag guard (``async/guard_hold_ms``);
    - ``bubble/learner_idle_ms`` — drain + guard hold, the learner's
      total idle (``async/learner_idle_ms`` when the async path
      reported it, else the drain alone);
    - ``bubble/admit_ms`` — the engine's host-side admission
      bookkeeping (``collect/admit`` span), the slot-refill stall.

    ``stats`` is a flat per-phase stats row (the trainer's
    ``_last_overlap_stats`` / a metrics-gauge dict). Absent sources
    yield no key — a fixed-sampler sync run reports only its drain."""
    out: Dict[str, float] = {}
    wall = phase_wall_ms(span_stats, phases)
    out["phase_wall_ms"] = wall

    def put(key: str, ms: float) -> None:
        out[f"bubble/{key}_ms"] = ms
        if wall > 0:
            out[f"bubble/{key}_frac"] = ms / wall

    if "train/drain" in span_stats:
        put("drain", float(span_stats["train/drain"]["total_ms"]) / max(1, phases))
    if "collect/admit" in span_stats:
        put("admit", float(span_stats["collect/admit"]["total_ms"]) / max(1, phases))
    stats = stats or {}
    if "async/guard_hold_ms" in stats:
        put("guard_hold", float(stats["async/guard_hold_ms"]))
    if "async/learner_idle_ms" in stats:
        put("learner_idle", float(stats["async/learner_idle_ms"]))
    elif "bubble/drain_ms" in out:
        put("learner_idle", out["bubble/drain_ms"])
    return out


def phase_goodput(
    span_stats: Dict[str, Dict[str, float]],
    samples_per_phase: int,
    phases: int = 1,
) -> Dict[str, float]:
    """Trained samples per second of total phase wall — the end-to-end
    goodput the per-program utilizations decompose. Charged against
    EVERY phase-level span (eval and checkpoint time are real wall the
    run spent not training)."""
    wall = phase_wall_ms(span_stats, phases)
    out = {"phase_wall_ms": wall}
    if wall > 0:
        out["goodput_samples_per_sec"] = samples_per_phase / (wall / 1000.0)
    return out


# ------------------------------ live tracing ------------------------------ #


def trainer_program_resources(
    trainer,
    kind: str = "ppo",
    chunk_size: Optional[int] = None,
    residual_len: Optional[int] = None,
) -> Dict[str, Dict[str, Any]]:
    """Engine-7 statics for a LIVE trainer's phase programs at the real
    workload shape (tracing only — no compilation): the train step, the
    compiled sampler at the orchestrator's chunk shape, and (when the
    trainer has one) the residual fused train_phase at
    ``residual_len`` stacked minibatches. The continuous engine's
    programs, when built, are traced through the analysis harness.

    Returns ``{subject: ProgramResources.to_dict()}`` — the
    :func:`attribute` input. Each program is individually guarded: a
    shape drift in one trace drops that row, never the table."""
    import jax
    import jax.numpy as jnp

    from trlx_tpu.analysis import harness
    from trlx_tpu.analysis.resource_audit import analyze_closed_jaxpr
    from trlx_tpu.parallel.mesh import batch_sharding

    out: Dict[str, Dict[str, Any]] = {}
    axis_sizes = {k: int(v) for k, v in trainer.mesh.shape.items()}
    state_sds = harness._sds(trainer.state)

    try:
        mb = (
            harness._ilql_minibatch_sds(trainer)
            if kind == "ilql"
            else harness._ppo_minibatch_sds(trainer)
        )
        closed = jax.make_jaxpr(trainer._train_step_jit)(state_sds, mb)
        divisors = harness.flat_sharding_divisors(
            (state_sds, mb),
            (trainer.state_shardings, batch_sharding(trainer.mesh)),
        )
        out[f"{kind}.train_step"] = analyze_closed_jaxpr(
            closed, f"{kind}.train_step", axis_sizes, divisors
        ).to_dict()
    except Exception:
        pass

    try:
        B = int(chunk_size or trainer.config.train.batch_size)
        Q = trainer.query_length
        prompt = jax.ShapeDtypeStruct((B, Q), jnp.int32)
        key = jax.ShapeDtypeStruct((2,), jnp.uint32)
        params_sds = harness._sds(trainer.state.params)
        closed = jax.make_jaxpr(trainer._sample_jit)(
            params_sds, prompt, prompt, key
        )
        divisors = harness.flat_sharding_divisors(
            (params_sds, prompt, prompt, key),
            (
                trainer.state_shardings.params,
                batch_sharding(trainer.mesh),
                batch_sharding(trainer.mesh),
                None,
            ),
        )
        out[f"{kind}.rollout"] = analyze_closed_jaxpr(
            closed, f"{kind}.rollout", axis_sizes, divisors
        ).to_dict()
    except Exception:
        pass

    if residual_len and residual_len > 0:
        try:
            from trlx_tpu.parallel.mesh import stacked_batch_sharding

            stacked = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    (int(residual_len),) + x.shape, x.dtype
                ),
                mb,
            )
            closed = jax.make_jaxpr(trainer._train_phase_jit)(
                state_sds, stacked
            )
            divisors = harness.flat_sharding_divisors(
                (state_sds, stacked),
                (
                    trainer.state_shardings,
                    stacked_batch_sharding(trainer.mesh),
                ),
            )
            out[f"{kind}.train_phase"] = analyze_closed_jaxpr(
                closed, f"{kind}.train_phase", axis_sizes, divisors
            ).to_dict()
        except Exception:
            pass

    if getattr(trainer, "_rollout_engine_obj", None) is not None:
        try:
            mesh_shape = {k: int(v) for k, v in trainer.mesh.shape.items()}
            for traced in harness._trace_engine_programs(
                trainer, kind, mesh_shape
            ):
                from trlx_tpu.analysis.resource_audit import (
                    analyze_traced_program,
                )

                out[traced.subject] = analyze_traced_program(traced).to_dict()
        except Exception:
            pass
    return out


# -------------------------------- rendering ------------------------------- #


def format_attribution(
    rows: Sequence[AttributionRow],
    bubbles: Optional[Dict[str, float]] = None,
    goodput: Optional[Dict[str, float]] = None,
) -> str:
    """The per-run "where did the time go" table (bench prints this to
    stderr; the JSON payload carries the same rows machine-readably)."""
    lines = ["utilization attribution (engine-7 statics ÷ span wall):"]
    header = (
        f"  {'program':24} {'window':22} {'calls':>7} {'wall ms':>10} "
        f"{'TFLOP/s':>9} {'MFU':>7} {'GB/s':>8} {'HBM%':>6}"
    )
    lines.append(header)
    nominal = False
    for r in rows:
        nominal = nominal or r.peak_nominal
        # significant digits, not fixed decimals: tiny-shape/CPU runs
        # produce MFUs like 4e-5 that fixed-point would render as 0
        mfu = f"{r.mfu:>7.3g}" if r.mfu is not None else f"{'—':>7}"
        bw = (
            f"{100 * r.hbm_util:>6.3g}"
            if r.hbm_util is not None
            else f"{'—':>6}"
        )
        lines.append(
            f"  {r.program:24} {r.span:22} {r.calls:>7.0f} "
            f"{r.wall_ms:>10.1f} {r.achieved_tflops_per_dev:>9.3g} "
            f"{mfu} {r.achieved_gbps_per_dev:>8.3g} {bw}"
        )
    if not rows:
        lines.append("  (no program/span pairs observed)")
    if nominal:
        lines.append(
            "  (utilizations priced off NOMINAL peaks — no published "
            "spec for this backend; compare round-over-round only)"
        )
    if bubbles:
        lines.append("async bubble breakdown (per phase):")
        wall = bubbles.get("phase_wall_ms", 0.0)
        lines.append(f"  phase wall            {wall:>10.1f} ms")
        for key in sorted(bubbles):
            if not key.startswith("bubble/") or not key.endswith("_ms"):
                continue
            name = key[len("bubble/"):-len("_ms")]
            frac = bubbles.get(f"bubble/{name}_frac")
            pct = f" ({100 * frac:.1f}% of phase)" if frac is not None else ""
            lines.append(f"  {name:20} {bubbles[key]:>12.1f} ms{pct}")
    if goodput and "goodput_samples_per_sec" in goodput:
        lines.append(
            f"goodput: {goodput['goodput_samples_per_sec']:.2f} trained "
            f"samples/s over {goodput['phase_wall_ms']:.1f} ms phase wall"
        )
    return "\n".join(lines)
