"""CLI: ``python -m trlx_tpu.telemetry --inspect <dump.json>``.

Renders a flight-recorder forensics dump (docs/observability.md,
"Flight recorder") as the human triage view: run header + error, the
tripped-detector table, the last-good-phase stats diff, and span p50
deltas. ``--json`` re-emits a machine-readable summary instead.

Exit status: 0 on a parseable dump, 2 on an unreadable/incompatible
file. (The dump's *content* never affects the exit code — this is a
viewer, not a gate.)
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m trlx_tpu.telemetry",
        description="inspect run-health flight-recorder dumps",
    )
    parser.add_argument(
        "--inspect",
        metavar="DUMP",
        required=True,
        help="path to a flight-recorder JSON dump",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="emit a machine-readable summary instead of the triage view",
    )
    args = parser.parse_args(argv)

    from trlx_tpu.telemetry.flight_recorder import inspect_dump, load_dump

    try:
        payload = load_dump(args.inspect)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: cannot read {args.inspect}: {e}", file=sys.stderr)
        return 2

    if args.json:
        events = payload.get("events") or []
        counts: dict = {}
        for e in events:
            det = e.get("detector", "?")
            counts[det] = counts.get(det, 0) + 1
        print(
            json.dumps(
                {
                    "reason": payload.get("reason"),
                    "fingerprint": payload.get("fingerprint"),
                    "error": payload.get("error"),
                    "phases_recorded": len(payload.get("phases") or []),
                    "event_counts": counts,
                }
            )
        )
    else:
        print(inspect_dump(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main())
