"""CLI: flight-dump triage, run comparison, run watching, trace reports.

Four subtools behind one entry point (docs/observability.md):

- ``python -m trlx_tpu.telemetry --inspect <dump.json>`` — render a
  flight-recorder forensics dump as the human triage view: run header +
  error, the tripped-detector table, the last-good-phase stats diff,
  span p50 deltas, and the final phase's metrics snapshot (including
  the per-tenant ``serve/*[tenant=…]`` histogram rows). ``--json``
  re-emits a machine-readable summary instead.
- ``python -m trlx_tpu.telemetry --compare <run_a> <run_b>`` — resolve
  two run-ledger manifests (run_id, ledger index like ``-1``, or a
  manifest file path; ``--ledger`` overrides ``$TRLX_RUN_LEDGER``) and
  render the regression diff: movers by relative delta, span p50s,
  attribution MFU per program.
- ``python -m trlx_tpu.telemetry --watch <run_dir>`` — tail the live
  ``phases.jsonl`` a ``train.run_dir`` run mirrors its phase records
  into, one line per phase (``--no-follow`` renders what exists and
  exits — the CI/test mode).
- ``python -m trlx_tpu.telemetry --trace-report <spans.jsonl>`` —
  per-request critical-path decomposition, per-tenant/SLO-class tail
  breakdown, and the decode-cadence bubble estimate over an exported
  span log carrying request traces (telemetry/trace_report.py;
  docs/observability.md "Request tracing").

Exit status: 0 on success, 2 on unreadable/unresolvable inputs. (The
content never affects the exit code — these are viewers, not gates.)
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m trlx_tpu.telemetry",
        description=(
            "inspect flight dumps, compare run-ledger manifests, watch "
            "live runs"
        ),
    )
    parser.add_argument(
        "--inspect",
        metavar="DUMP",
        help="path to a flight-recorder JSON dump",
    )
    parser.add_argument(
        "--compare",
        nargs=2,
        metavar=("RUN_A", "RUN_B"),
        help=(
            "two runs to diff: run_id, ledger index (-1 newest), or a "
            "manifest JSON path"
        ),
    )
    parser.add_argument(
        "--ledger",
        metavar="PATH",
        default=None,
        help="ledger JSONL for --compare run resolution "
        "(default: $TRLX_RUN_LEDGER or RUN_LEDGER.jsonl)",
    )
    parser.add_argument(
        "--watch",
        metavar="RUN_DIR",
        help="tail a run's live phases.jsonl (a train.run_dir)",
    )
    parser.add_argument(
        "--no-follow",
        action="store_true",
        help="with --watch: render the rows on disk and exit",
    )
    parser.add_argument(
        "--trace-report",
        metavar="SPANS",
        help=(
            "span JSONL with per-request traces: render the "
            "critical-path / tenant-tail / decode-bubble report"
        ),
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="emit a machine-readable summary instead of the triage view",
    )
    args = parser.parse_args(argv)

    if args.trace_report:
        from trlx_tpu.telemetry.trace_report import (
            render_report,
            report_json,
        )

        try:
            if args.json:
                print(json.dumps(report_json(args.trace_report)))
            else:
                print(render_report(args.trace_report))
        except OSError as e:
            print(
                f"error: cannot read {args.trace_report}: {e}",
                file=sys.stderr,
            )
            return 2
        return 0

    if args.compare:
        from trlx_tpu.telemetry.run_ledger import compare_runs, resolve_run

        try:
            run_a = resolve_run(args.compare[0], args.ledger)
            run_b = resolve_run(args.compare[1], args.ledger)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.json:
            from trlx_tpu.telemetry.run_ledger import flatten_numeric

            flat_a, flat_b = flatten_numeric(run_a), flatten_numeric(run_b)
            deltas = {
                k: {"a": flat_a[k], "b": flat_b[k]}
                for k in sorted(set(flat_a) & set(flat_b))
                if flat_a[k] != flat_b[k]
            }
            print(
                json.dumps(
                    {
                        "run_a": run_a.get("run_id"),
                        "run_b": run_b.get("run_id"),
                        "deltas": deltas,
                    }
                )
            )
        else:
            print(compare_runs(run_a, run_b))
        return 0

    if args.watch:
        from trlx_tpu.telemetry.run_ledger import watch

        try:
            watch(args.watch, follow=not args.no_follow)
        except FileNotFoundError as e:
            print(f"error: no phase log at {e}", file=sys.stderr)
            return 2
        return 0

    if args.inspect:
        from trlx_tpu.telemetry.flight_recorder import inspect_dump, load_dump

        try:
            payload = load_dump(args.inspect)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: cannot read {args.inspect}: {e}", file=sys.stderr)
            return 2

        if args.json:
            events = payload.get("events") or []
            counts: dict = {}
            for e in events:
                det = e.get("detector", "?")
                counts[det] = counts.get(det, 0) + 1
            print(
                json.dumps(
                    {
                        "reason": payload.get("reason"),
                        "fingerprint": payload.get("fingerprint"),
                        "error": payload.get("error"),
                        "phases_recorded": len(payload.get("phases") or []),
                        "event_counts": counts,
                    }
                )
            )
        else:
            print(inspect_dump(payload))
        return 0

    parser.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
