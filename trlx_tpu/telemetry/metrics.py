"""Typed rank-0 metrics registry: one snapshot-able namespace.

Before this module, every subsystem kept its own ad-hoc stats dict —
``EngineStats.to_dict()`` (``engine/*`` occupancy), the trainer's
``_last_overlap_stats`` (``async/staleness_*``, ``async/learner_idle_ms``,
``mem/hbm_*``), the serving path's per-group health row — each with its
own lifetime and no way to ask "what does this process know about itself
right now". The :class:`MetricsRegistry` is the absorbing layer: three
typed instruments with the usual semantics,

- :class:`Counter` — monotone ``inc()``; totals (requests served,
  decode steps);
- :class:`Gauge` — ``set()`` last-value, plus a bounded ``(t, value)``
  sample ring on the shared telemetry clock so a gauge is also a
  timeseries (the Perfetto counter-track export reads it);
- :class:`Histogram` — ``observe()`` with cumulative count/sum/min/max
  and a bounded window for p50/p95 (serving request latencies);

``snapshot()`` renders the whole namespace as plain JSON-able dicts —
the run ledger, the flight recorder, and the bench payload all embed it.

Cost model mirrors the tracer (the registry sits on host hot paths like
the engine's done-poll loop): **enabled** — one dict lookup per
``counter(name)``-style access plus one float op per mutation;
**disabled** — instrument accessors return the shared
:data:`NULL_INSTRUMENT` singleton (one attribute read, nothing
allocated, nothing recorded). Rank-0 gating follows the tracer's
(``TRLX_TELEMETRY`` overrides; multi-host pods meter the main process
only).

Single-thread contract (engine 14 allowlist,
``analysis/concurrency.py``): the instrument TABLE is guarded by the
registry's ``_lock`` (creation may race), but the instruments
themselves are a rank-0 **main-thread** namespace — mutated and
snapshot from the trainer's host loop (the engine's drive thread and
the serving pump run on that same loop). Nothing here is safe to
mutate from the background writer thread or a learner-pusher thread;
cross-thread code must hand values to the host loop and let it record
them. The ``--races`` lockset walk encodes this by allowlisting the
class instead of demanding a lock on the per-mutation hot paths.

Module is stdlib-only at import time (the clock comes from
:mod:`trlx_tpu.telemetry.tracer`, itself stdlib-only).
"""

from __future__ import annotations

import threading
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from trlx_tpu.telemetry.tracer import monotonic, quantile

#: bound on each gauge's (t, value) sample ring and each histogram's
#: percentile window — memory stays bounded on arbitrarily long runs
DEFAULT_MAX_SAMPLES = 4096


class _NullInstrument:
    """Shared no-op instrument returned while the registry is disabled:
    every mutator exists on the one singleton, so a disabled hot path
    costs an attribute read and a call."""

    __slots__ = ()

    name = ""
    value = 0.0

    def inc(self, n: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass


NULL_INSTRUMENT = _NullInstrument()


class Counter:
    """Monotone counter. ``inc`` only — a counter that can go down is a
    gauge wearing the wrong type (the registry enforces the split)."""

    __slots__ = ("name", "value")

    kind = "counter"

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n


class Gauge:
    """Last-value gauge with a bounded timeseries: every ``set`` appends
    ``(monotonic(), value)`` to the sample ring, so occupancy /
    live-HBM gauges double as Perfetto counter tracks
    (:func:`~trlx_tpu.telemetry.tracer.chrome_counter_events`)."""

    __slots__ = ("name", "value", "samples")

    kind = "gauge"

    def __init__(self, name: str, max_samples: int = DEFAULT_MAX_SAMPLES):
        self.name = name
        self.value = 0.0
        self.samples: "deque[Tuple[float, float]]" = deque(
            maxlen=max_samples
        )

    def set(self, value: float) -> None:
        v = float(value)
        self.value = v
        self.samples.append((monotonic(), v))


class Histogram:
    """Distribution instrument: cumulative count/sum/min/max plus a
    bounded recent window for nearest-rank percentiles (the same
    estimator the span stats use)."""

    __slots__ = ("name", "count", "sum", "min", "max", "_window")

    kind = "histogram"

    def __init__(self, name: str, max_samples: int = DEFAULT_MAX_SAMPLES):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._window: "deque[float]" = deque(maxlen=max_samples)

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        self._window.append(v)

    def summary(self) -> Dict[str, float]:
        if not self.count:
            return {"count": 0}
        durs = sorted(self._window)
        return {
            "count": self.count,
            "mean": self.sum / self.count,
            "p50": quantile(durs, 0.5),
            "p95": quantile(durs, 0.95),
            "min": self.min,
            "max": self.max,
        }


class MetricsRegistry:
    """Get-or-create instrument namespace. Thread-safe creation (the
    engine's drive loop and the background writer both meter); mutation
    is per-instrument and relies on the GIL like the tracer's ring."""

    def __init__(
        self,
        enabled: bool = True,
        max_samples: int = DEFAULT_MAX_SAMPLES,
    ):
        self.enabled = enabled
        self.max_samples = int(max_samples)
        self._instruments: Dict[str, Any] = {}
        self._lock = threading.Lock()

    # ------------------------------ access ------------------------------ #

    def _get(self, name: str, cls, **kwargs):
        if not self.enabled:
            return NULL_INSTRUMENT
        inst = self._instruments.get(name)
        if inst is None:
            with self._lock:
                inst = self._instruments.get(name)
                if inst is None:
                    inst = cls(name, **kwargs)
                    self._instruments[name] = inst
        if not isinstance(inst, cls):
            raise TypeError(
                f"metric {name!r} is a {inst.kind}, not a "
                f"{cls.kind} — one name, one type"
            )
        return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge, max_samples=self.max_samples)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram, max_samples=self.max_samples)

    def absorb(
        self, row: Optional[Dict[str, Any]], prefix: str = ""
    ) -> int:
        """Fold an ad-hoc stats dict into the registry as gauges (the
        migration path for ``engine/*`` occupancy, ``async/*``
        attribution, ``mem/hbm_*`` rows): numeric values become
        ``gauge(prefix + key).set(value)``; everything else is skipped.
        Returns the number of gauges set."""
        if not self.enabled or not row:
            return 0
        n = 0
        for key, value in row.items():
            if isinstance(value, bool) or not isinstance(
                value, (int, float)
            ):
                continue
            self.gauge(prefix + key).set(float(value))
            n += 1
        return n

    # ----------------------------- reading ------------------------------ #

    def clear(self) -> None:
        with self._lock:
            self._instruments.clear()

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """The whole namespace as plain dicts:
        ``{"counters": {name: value}, "gauges": {name: value},
        "histograms": {name: summary}}`` — JSON-able, embedded verbatim
        by the run ledger / flight recorder / bench payload."""
        with self._lock:
            instruments = list(self._instruments.values())
        out: Dict[str, Dict[str, Any]] = {
            "counters": {},
            "gauges": {},
            "histograms": {},
        }
        for inst in sorted(instruments, key=lambda i: i.name):
            if isinstance(inst, Counter):
                out["counters"][inst.name] = inst.value
            elif isinstance(inst, Gauge):
                out["gauges"][inst.name] = inst.value
            elif isinstance(inst, Histogram):
                out["histograms"][inst.name] = inst.summary()
        return out

    def gauge_series(
        self, names: Optional[Iterable[str]] = None
    ) -> Dict[str, List[Tuple[float, float]]]:
        """Per-gauge ``(t, value)`` samples (every gauge, or ``names``)
        — the Perfetto counter-track export's input."""
        with self._lock:
            instruments = list(self._instruments.values())
        wanted = set(names) if names is not None else None
        out: Dict[str, List[Tuple[float, float]]] = {}
        for inst in instruments:
            if not isinstance(inst, Gauge) or not inst.samples:
                continue
            if wanted is not None and inst.name not in wanted:
                continue
            out[inst.name] = list(inst.samples)
        return out


def split_metric_label(name: str) -> "Tuple[str, str]":
    """``(base, label)`` of a possibly-labeled metric name:
    ``serve/queue_wait_ms[tenant=gold]`` → ``("serve/queue_wait_ms",
    "[tenant=gold]")``; unlabeled names return ``(name, "")``. One
    parser for every consumer of the flat ``[k=v]``-suffix convention
    (tenant histograms today)."""
    if name.endswith("]"):
        cut = name.find("[")
        if cut > 0:
            return name[:cut], name[cut:]
    return name, ""


def flatten_snapshot(
    snap: Optional[Dict[str, Dict[str, Any]]]
) -> Dict[str, float]:
    """A :meth:`MetricsRegistry.snapshot` as one flat numeric dict —
    counters/gauges keep their names, histogram summaries flatten to
    ``name/p50``-style keys. The run-ledger movers diff compares these.

    Labeled histogram names keep their label TERMINAL:
    ``serve/queue_wait_ms[tenant=gold]`` flattens to
    ``serve/queue_wait_ms/p50[tenant=gold]`` — the metric family stays
    one contiguous prefix, so the ``--compare`` movers diff sorts and
    matches tenant-labeled series next to their aggregates instead of
    splitting the family at the bracket."""
    out: Dict[str, float] = {}
    if not snap:
        return out
    for name, value in (snap.get("counters") or {}).items():
        out[name] = float(value)
    for name, value in (snap.get("gauges") or {}).items():
        out[name] = float(value)
    for name, summary in (snap.get("histograms") or {}).items():
        base, label = split_metric_label(name)
        for stat, value in (summary or {}).items():
            out[f"{base}/{stat}{label}"] = float(value)
    return out


# ------------------------------ global wiring ----------------------------- #

_registry: Optional[MetricsRegistry] = None


def get_metrics() -> MetricsRegistry:
    """The process-global registry (created on first use; enabled on
    rank 0 by default, same gating as the tracer)."""
    global _registry
    if _registry is None:
        from trlx_tpu.telemetry import _default_enabled

        _registry = MetricsRegistry(enabled=_default_enabled())
    return _registry


def configure_metrics(
    enabled: Optional[bool] = None, max_samples: Optional[int] = None
) -> MetricsRegistry:
    """Adjust the global registry; returns it."""
    registry = get_metrics()
    if enabled is not None:
        registry.enabled = bool(enabled)
    if max_samples is not None:
        registry.max_samples = int(max_samples)
    return registry


@contextmanager
def scoped_metrics(registry: Optional[MetricsRegistry] = None):
    """Temporarily install ``registry`` (default: a fresh enabled one)
    as the process-global registry — the metrics twin of
    :func:`~trlx_tpu.telemetry.scoped_tracer`, for harnesses and tests
    that must neither wipe nor leak into the embedding process's
    namespace."""
    global _registry
    prev = get_metrics()
    installed = (
        registry if registry is not None else MetricsRegistry(enabled=True)
    )
    _registry = installed
    try:
        yield installed
    finally:
        _registry = prev
