"""Crash-forensics flight recorder: the last N phases, dumped on failure.

When a run dies — an uncaught exception in the phase loop, a detector
hitting the ``dump``/``abort`` policy, or an operator asking via
``train.flight_dump_phase`` — the post-mortem today is whatever wandb
happened to flush. The :class:`FlightRecorder` keeps a bounded ring of
per-phase records (the fetched stats row, the KL sequence, the span-tree
aggregate, allocator gauges, detector EWMA state, tripped events) and
writes ONE self-contained JSON forensics file on the way down, stamped
with the config fingerprint so the artifact self-identifies.

Recording costs nothing device-side: every field is data the phase loop
already holds on host (the stats row it fetched, ``tracer.stats()``
aggregates, ``device_metrics.snapshot()`` gauges that are empty on CPU).

``python -m trlx_tpu.telemetry --inspect <dump.json>`` renders the
triage view: tripped detectors, the last-good-phase stats diff (what
moved between the last healthy phase and the crash), and span p50
deltas (did the machine slow down as the learning went bad).
"""

from __future__ import annotations

import json
import os
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

SCHEMA_VERSION = 1


def _span_stats_window(
    since_index: int,
) -> "tuple[Dict[str, Dict[str, float]], int]":
    """Per-name span stats over the spans closed SINCE ``since_index``
    (the previous phase record), not run-cumulative aggregates — a
    100-phase run's final slow phase must move its record's p50s, and a
    cumulative nearest-rank p50 would dilute one slow sample to
    nothing. Returns (stats, new_high_watermark)."""
    from trlx_tpu import telemetry

    try:
        all_spans = telemetry.get_tracer().spans()
    except Exception:
        return {}, since_index
    if all_spans and max(s.index for s in all_spans) < since_index:
        # the tracer was cleared (indices restarted at 0, e.g. bench's
        # measured-window clear): a stale watermark would filter every
        # span forever — restart the window
        since_index = -1
    spans = [s for s in all_spans if s.index > since_index]
    if not spans:
        return {}, since_index
    groups: Dict[str, list] = {}
    high = since_index
    for s in spans:
        groups.setdefault(s.name, []).append(s.duration_ms)
        high = max(high, s.index)
    out: Dict[str, Dict[str, float]] = {}
    for name, durs in sorted(groups.items()):
        durs.sort()
        out[name] = {
            "count": float(len(durs)),
            "p50_ms": telemetry.quantile(durs, 0.5),
            "p95_ms": telemetry.quantile(durs, 0.95),
            "total_ms": sum(durs),
        }
    return out, high


def _memory_snapshot() -> Dict[str, int]:
    try:
        from trlx_tpu.telemetry.device_metrics import snapshot

        return snapshot()
    except Exception:
        return {}


def _metrics_snapshot() -> Dict[str, Any]:
    """The metrics-registry namespace at this phase boundary (counters,
    gauges, histogram summaries) — empty when the registry is disabled
    or telemetry import fails (forensics must never add a failure
    mode)."""
    try:
        from trlx_tpu import telemetry

        return telemetry.get_metrics().snapshot()
    except Exception:
        return {}


class FlightRecorder:
    """Bounded ring of phase records + the dump that ships them.

    One recorder per trainer (rank-0 only, built by the base trainer
    when ``train.health.enabled``). Not thread-safe by design: records
    land from the phase loop's thread at phase boundaries.
    """

    def __init__(
        self,
        capacity: int = 16,
        directory: str = "health_dumps",
        fingerprint: str = "",
        config: Optional[Dict[str, Any]] = None,
    ):
        self.capacity = int(capacity)
        self.directory = directory
        self.fingerprint = fingerprint
        self._config = config
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        # run-level event mirror: deduped (the detector-trip dump path
        # records the offending row's events, then the phase epilogue
        # records the same phase's events again) and bounded
        self._all_events: List[Dict[str, Any]] = []
        self._event_keys: set = set()
        self._max_events = 512
        self._span_watermark = -1  # spans already covered by a record
        self.dumped: List[str] = []
        self._dump_reasons: set = set()
        self._exception_dumped = False
        self._seq = 0

    def __len__(self) -> int:
        return len(self._ring)

    # ------------------------------ recording ----------------------------- #

    def record_phase(
        self,
        phase: Optional[int],
        step: Optional[int] = None,
        stats_row: Optional[Dict[str, Any]] = None,
        kl_seq: Optional[Sequence[float]] = None,
        events: Sequence[Any] = (),
        detector_state: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Append one phase record to the ring. ``stats_row`` is the
        phase's last fetched stats row (host floats; device leaves are
        dropped, never forced); ``events`` are the phase's
        :class:`~trlx_tpu.telemetry.health.HealthEvent` trips."""
        from trlx_tpu.telemetry.health import _host_float

        row: Dict[str, float] = {}
        for key, value in (stats_row or {}).items():
            v = _host_float(value)
            if v is not None:
                row[key] = v
        event_dicts = [
            e.to_dict() if hasattr(e, "to_dict") else dict(e) for e in events
        ]
        has_error = any(
            e.get("severity") == "error" for e in event_dicts
        )
        spans, self._span_watermark = _span_stats_window(self._span_watermark)
        rec = {
            "phase": phase,
            "step": step,
            "stats": row,
            "kl_seq": [float(k) for k in (kl_seq or [])],
            "spans": spans,
            "memory": _memory_snapshot(),
            "metrics": _metrics_snapshot(),
            "events": event_dicts,
            "detectors": detector_state or {},
            "good": not has_error,
            "recorded_unix": time.time(),
        }
        self._ring.append(rec)
        self._fold_events(event_dicts)
        return rec

    def _fold_events(
        self, event_dicts: List[Dict[str, Any]]
    ) -> List[Dict[str, Any]]:
        """Dedupe ``event_dicts`` into the bounded run-level mirror;
        returns the genuinely-new ones."""
        fresh: List[Dict[str, Any]] = []
        for e in event_dicts:
            ekey = (
                e.get("detector"), e.get("series"),
                e.get("step"), e.get("value"),
            )
            if ekey not in self._event_keys:
                self._event_keys.add(ekey)
                self._all_events.append(e)
                fresh.append(e)
        if len(self._all_events) > self._max_events:
            del self._all_events[: len(self._all_events) - self._max_events]
        return fresh

    def note_events(
        self,
        events: Sequence[Any],
        detector_state: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Fold events into the run mirror AND the newest ring record
        WITHOUT appending a new record — the exception-dump path uses
        this for events a crash preempted out of a phase epilogue. A
        fresh stats-less record here would become the dump's final
        phase and empty the --inspect last-good stats diff."""
        event_dicts = [
            e.to_dict() if hasattr(e, "to_dict") else dict(e) for e in events
        ]
        fresh = self._fold_events(event_dicts)
        if not self._ring:
            if fresh:
                self.record_phase(
                    None, events=fresh, detector_state=detector_state
                )
            return
        rec = self._ring[-1]
        if fresh:
            rec["events"] = list(rec["events"]) + fresh
            if any(e.get("severity") == "error" for e in fresh):
                rec["good"] = False
        if detector_state:
            rec["detectors"] = detector_state

    # ------------------------------- dumping ------------------------------ #

    def _dump_path(self, reason: str) -> str:
        slug = "".join(
            c if c.isalnum() or c in "-_" else "-" for c in reason
        )[:48]
        self._seq += 1
        return os.path.join(
            self.directory,
            f"flight_{slug}_{os.getpid()}_{self._seq}.json",
        )

    def dump(
        self,
        reason: str,
        error: Optional[BaseException] = None,
        path: Optional[str] = None,
        once: bool = False,
    ) -> Optional[str]:
        """Write one self-contained forensics JSON; returns its path.

        ``once=True`` dedupes by ``reason`` (the detector ``dump``
        policy calls this per offending row — one anomaly, one file)."""
        if once and reason in self._dump_reasons:
            return None
        self._dump_reasons.add(reason)
        payload: Dict[str, Any] = {
            "schema_version": SCHEMA_VERSION,
            "reason": reason,
            "created_unix": time.time(),
            "fingerprint": self.fingerprint,
            "platform": _platform_info(),
            "config": self._config,
            "error": _error_info(error),
            "phases": list(self._ring),
            "events": list(self._all_events),
        }
        path = path or self._dump_path(reason)
        directory = os.path.dirname(path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, default=float)
        self.dumped.append(path)
        return path

    def dump_on_exception(self, error: BaseException) -> Optional[str]:
        """The uncaught-exception hook (learn epilogues, api.train): at
        most ONE exception dump per recorder, and none when the abort
        policy already dumped for the detector that raised."""
        if self._exception_dumped:
            return None
        from trlx_tpu.telemetry.health import HealthAbort

        self._exception_dumped = True
        if isinstance(error, HealthAbort) and self.dumped:
            return None  # the abort policy's dump already has the story
        return self.dump(
            f"exception:{type(error).__name__}", error=error
        )


def _platform_info() -> Dict[str, Any]:
    try:
        import jax

        devices = jax.devices()
        return {
            "backend": jax.default_backend(),
            "device_kind": devices[0].device_kind if devices else "",
            "n_devices": len(devices),
        }
    except Exception:
        return {}


def _error_info(error: Optional[BaseException]) -> Optional[Dict[str, str]]:
    if error is None:
        return None
    return {
        "type": type(error).__name__,
        "message": str(error),
        "traceback": "".join(
            traceback.format_exception(type(error), error, error.__traceback__)
        )[-4000:],
    }


# ------------------------------ inspection ------------------------------- #


def _fmt(v: float) -> str:
    if v == 0:
        return "0"
    if abs(v) >= 1e4 or abs(v) < 1e-3:
        return f"{v:.3e}"
    return f"{v:.4g}"


def inspect_dump(payload: Dict[str, Any]) -> str:
    """Human triage view of one flight dump (pure; the CLI prints it).

    Sections: run header, tripped-detector table, last-good vs final
    phase stats diff (largest relative movers), and span p50 deltas
    between those two phases."""
    lines: List[str] = []
    reason = payload.get("reason", "?")
    err = payload.get("error") or {}
    platform = payload.get("platform") or {}
    phases = payload.get("phases") or []
    events = payload.get("events") or []
    lines.append(f"flight dump: reason={reason}")
    lines.append(
        f"  fingerprint={payload.get('fingerprint', '')}  "
        f"platform={platform.get('backend', '?')}"
        f"/{platform.get('device_kind', '?')}"
        f"  phases_recorded={len(phases)}  events={len(events)}"
    )
    err_type = err.get("type")
    if err_type:
        lines.append(f"  error: {err_type}: {err.get('message', '')}")

    # tripped detectors
    if events:
        lines.append("")
        lines.append("tripped detectors:")
        by_det: Dict[str, List[Dict[str, Any]]] = {}
        for e in events:
            by_det.setdefault(e.get("detector", "?"), []).append(e)
        for det, evs in sorted(by_det.items()):
            first, last = evs[0], evs[-1]
            lines.append(
                f"  {det:20} x{len(evs):<3} [{last.get('severity', '?')}] "
                f"steps {first.get('step')}..{last.get('step')}  "
                f"last: {last.get('message', '')}"
            )
    else:
        lines.append("")
        lines.append("tripped detectors: none")

    # metrics-registry snapshot of the final recorded phase: the
    # unified namespace (engine occupancy, async bubbles, mem gauges,
    # serving histograms) the run held when it went down. Tenant-labeled
    # rows render in their own per-tenant table below — crowding them
    # into this capped list would truncate exactly the multi-tenant
    # triage the labels exist for.
    from trlx_tpu.telemetry.metrics import split_metric_label

    def tenant_of(name: str):
        """(base, tenant) via the one shared label parser — None tenant
        for unlabeled (or differently-labeled) names."""
        base, label = split_metric_label(name)
        if label.startswith("[tenant="):
            return base, label[len("[tenant="):-1]
        return name, None

    final_metrics = (phases[-1].get("metrics") or {}) if phases else {}
    flat_metrics: List[tuple] = []
    tenant_rows: List[tuple] = []  # (tenant, base metric, summary)
    scalar_tenant_rows: List[tuple] = []  # (tenant, name, rendered)
    for section in ("counters", "gauges"):
        for name, value in (final_metrics.get(section) or {}).items():
            base, tenant = tenant_of(name)
            if tenant is not None:
                scalar_tenant_rows.append(
                    (tenant, base, _fmt(float(value)))
                )
            else:
                flat_metrics.append((name, _fmt(float(value))))
    for name, summary in (final_metrics.get("histograms") or {}).items():
        if not summary.get("count"):
            continue
        base, tenant = tenant_of(name)
        if tenant is not None:
            tenant_rows.append((tenant, base, summary))
            continue
        flat_metrics.append(
            (
                name,
                f"p50={_fmt(float(summary.get('p50', 0.0)))} "
                f"n={int(summary['count'])}",
            )
        )
    if flat_metrics:
        lines.append("")
        lines.append("metrics snapshot (final phase):")
        for name, rendered in sorted(flat_metrics)[:16]:
            lines.append(f"  {name:32} {rendered:>16}")
        if len(flat_metrics) > 16:
            lines.append(f"  ... {len(flat_metrics) - 16} more")
    if tenant_rows or scalar_tenant_rows:
        lines.append("")
        lines.append("serving metrics by tenant (final phase):")
        lines.append(
            f"  {'tenant':12} {'metric':28} {'n':>6} {'p50':>10} "
            f"{'p95':>10} {'max':>10}"
        )
        for tenant, base, summary in sorted(tenant_rows):
            lines.append(
                f"  {tenant:12} {base:28} {int(summary['count']):>6} "
                f"{_fmt(float(summary.get('p50', 0.0))):>10} "
                f"{_fmt(float(summary.get('p95', 0.0))):>10} "
                f"{_fmt(float(summary.get('max', 0.0))):>10}"
            )
        for tenant, base, rendered in sorted(scalar_tenant_rows):
            lines.append(f"  {tenant:12} {base:28} {rendered:>6}")

    # last-good vs final phase
    final = phases[-1] if phases else None
    good = None
    for rec in reversed(phases[:-1] if len(phases) > 1 else []):
        if rec.get("good"):
            good = rec
            break
    if final is not None and good is not None:
        lines.append("")
        lines.append(
            f"last-good phase {good.get('phase')} -> final phase "
            f"{final.get('phase')} stats diff (largest relative movers):"
        )
        good_row = good.get("stats") or {}
        final_row = final.get("stats") or {}
        movers = []
        for key in sorted(set(good_row) & set(final_row)):
            a, b = float(good_row[key]), float(final_row[key])
            # signed relative move for DISPLAY (a collapse must read as
            # negative); magnitude only for ranking
            rel = (b - a) / max(abs(a), 1e-9)
            movers.append((abs(rel), key, a, b, rel))
        movers.sort(reverse=True)
        for _mag, key, a, b, rel in movers[:12]:
            lines.append(
                f"  {key:32} {_fmt(a):>12} -> {_fmt(b):>12} "
                f"({rel * 100.0:+.0f}%)"
            )
        good_spans = good.get("spans") or {}
        final_spans = final.get("spans") or {}
        span_rows = []
        for name in sorted(set(good_spans) & set(final_spans)):
            p50_a = float(good_spans[name].get("p50_ms", 0.0))
            p50_b = float(final_spans[name].get("p50_ms", 0.0))
            if p50_a > 0.0 or p50_b > 0.0:
                span_rows.append((name, p50_a, p50_b))
        if span_rows:
            lines.append("")
            lines.append("span p50 deltas (ms):")
            for name, a, b in span_rows:
                lines.append(f"  {name:32} {a:>10.2f} -> {b:>10.2f}")
    elif final is not None:
        lines.append("")
        lines.append(
            "no earlier good phase in the ring — every recorded phase "
            "carries error-severity events (raise health.flight_capacity "
            "to keep more history)"
        )
    return "\n".join(lines)


def load_dump(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as fh:
        payload = json.load(fh)
    version = payload.get("schema_version")
    if version != SCHEMA_VERSION:
        raise ValueError(
            f"{path}: flight-dump schema_version {version!r} != "
            f"{SCHEMA_VERSION} (written by a different build?)"
        )
    return payload
