"""Supervised auto-resume: bounded restarts from the last good checkpoint.

`api.train` runs each training attempt under :func:`run_supervised`.
With ``train.resilience.enabled`` the supervisor installs the
preemption guard, arms any configured chaos schedule, and classifies
every escape from the attempt:

- **preemption** (:class:`PreemptionDrain` — the trainer already wrote
  an emergency checkpoint at the phase boundary): restart resuming from
  it, unless ``resume_on_preemption`` is off (real preemptions usually
  want the *next* scheduled job to resume; the in-process restart is
  what makes kill/resume testable end-to-end);
- **retriable** (transient host I/O per the `utils/retry.py` taxonomy,
  or a :class:`HealthAbort` — ``health.on_error: abort`` feeds the
  supervisor, docs/observability.md): restart from the latest good
  checkpoint;
- **permanent** (structure mismatch, config errors, NaN divergence —
  deterministic failures a restart replays): re-raise immediately.

Restarts are bounded by ``max_restarts``; exhausting the budget raises
:class:`RestartBudgetExhausted` chaining the last failure. Each attempt
rebuilds the trainer from scratch (mid-phase state is assumed poisoned)
and resumes only when a restorable checkpoint actually exists.
"""

from __future__ import annotations

import sys
import time
from dataclasses import dataclass, field, fields
from typing import Any, Callable, Dict, List, Optional

from trlx_tpu.resilience import chaos, preemption
from trlx_tpu.resilience.preemption import PreemptionDrain
from trlx_tpu.utils.retry import (
    RetryPolicy,
    classify_io_error,
    set_default_policy,
)


class RestartBudgetExhausted(RuntimeError):
    """The supervisor's restart budget ran out; the last attempt's
    failure is chained as ``__cause__``."""


@dataclass
class ResilienceConfig:
    """``train.resilience`` section (plain dict in YAML, parsed here).

    :param enabled: master switch — off (the default) changes nothing:
        no signal handlers, no retries beyond the module defaults, no
        supervisor loop.
    :param max_restarts: restarts (not attempts) the supervisor may
        spend on retriable failures/preemptions.
    :param restart_delay_s: base delay before a restart, doubled per
        consecutive restart (a crash-looping dependency gets backoff,
        not a tight loop).
    :param resume_on_preemption: restart in-process after a preemption
        drain (False re-raises so the scheduler's next job resumes).
    :param preempt_signals: signal names the guard intercepts.
    :param retry: `utils/retry.py` RetryPolicy overrides applied to
        every wrapped I/O path (checkpoint save/load, writer, admission).
    :param chaos: fault-injection specs (resilience/chaos.py) armed for
        the supervised run — the config-driven face of ``TRLX_CHAOS``.
    """

    enabled: bool = False
    max_restarts: int = 2
    restart_delay_s: float = 0.0
    resume_on_preemption: bool = True
    preempt_signals: List[str] = field(
        default_factory=lambda: ["SIGTERM", "SIGINT"]
    )
    retry: Dict[str, Any] = field(default_factory=dict)
    chaos: List[Dict[str, Any]] = field(default_factory=list)

    @classmethod
    def from_dict(cls, config: Optional[Dict[str, Any]]) -> "ResilienceConfig":
        config = dict(config or {})
        known = {f.name for f in fields(cls)}
        unknown = set(config) - known
        if unknown:
            raise ValueError(
                f"Unknown train.resilience keys: {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        out = cls(**config)
        if out.max_restarts < 0:
            raise ValueError("train.resilience.max_restarts must be >= 0")
        RetryPolicy.from_dict(out.retry)  # validate keys early
        return out


def failure_kind(error: BaseException) -> str:
    """``preemption`` | ``retriable`` | ``permanent`` for the supervisor.

    HealthAbort is retriable by design: the detector already dumped the
    forensics file, and the whole point of ``on_error: abort`` under a
    supervisor is "stop digging, restore the last good checkpoint".
    ActorDeadError (async actor–learner, docs/async_pipeline.md) is
    retriable for the same reason: the orchestrator already emitted the
    ``actor-dead`` health event, the learner's checkpoint is intact,
    and a restart rebuilds the actor pool from scratch — the dead-actor
    recovery story. NaN-divergence RuntimeErrors and every other
    deterministic failure stay permanent — replaying them from a
    checkpoint written *before* the divergence re-fails identically.
    """
    from trlx_tpu.telemetry.health import HealthAbort
    from trlx_tpu.trainer.async_rl import ActorDeadError

    if isinstance(error, PreemptionDrain):
        return "preemption"
    if isinstance(error, (HealthAbort, ActorDeadError)):
        return "retriable"
    if not isinstance(error, Exception):
        return "permanent"  # KeyboardInterrupt / SystemExit: never eat
    if classify_io_error(error) == "transient":
        return "retriable"
    return "permanent"


def run_supervised(
    attempt: Callable[[bool], Any],
    config,
    *,
    sleep: Callable[[float], None] = time.sleep,
) -> Any:
    """Run ``attempt(resume)`` under the resilience policy of
    ``config.train.resilience``. Disabled → exactly one attempt with the
    caller's ``resume_from_checkpoint``, no handlers touched."""
    rc = ResilienceConfig.from_dict(config.train.resilience)
    if not rc.enabled:
        return attempt(bool(config.train.resume_from_checkpoint))

    from trlx_tpu.utils.checkpoint import has_checkpoint

    preemption.install_guard(rc.preempt_signals)
    if rc.retry:
        set_default_policy(RetryPolicy.from_dict(rc.retry))
    # unconditional: configure() also merges TRLX_CHAOS env specs — the
    # "no code/config changes" injection path must arm even when the
    # config carries no chaos list of its own
    chaos.configure(rc.chaos)
    restarts = 0
    resume = bool(config.train.resume_from_checkpoint)
    try:
        while True:
            try:
                return attempt(resume)
            except BaseException as error:
                kind = failure_kind(error)
                if kind == "permanent":
                    raise
                if kind == "preemption" and not rc.resume_on_preemption:
                    raise
                restarts += 1
                if restarts > rc.max_restarts:
                    raise RestartBudgetExhausted(
                        f"restart budget exhausted "
                        f"({rc.max_restarts} restarts) — last failure: "
                        f"{type(error).__name__}: {error}"
                    ) from error
                preemption.clear_request()
                resume = has_checkpoint(config.train.checkpoint_dir)
                print(
                    f"resilience: restart {restarts}/{rc.max_restarts} "
                    f"after {kind} ({type(error).__name__}: {error}) — "
                    + (
                        "resuming from "
                        f"{config.train.checkpoint_dir!r}"
                        if resume
                        else "no checkpoint yet, starting fresh"
                    ),
                    file=sys.stderr,
                )
                if rc.restart_delay_s > 0:
                    sleep(rc.restart_delay_s * (2 ** (restarts - 1)))
    finally:
        preemption.uninstall_guard()
        chaos.clear()
        if rc.retry:
            set_default_policy(None)
