"""Fault tolerance: chaos injection, preemption drain, supervised resume.

The recovery half of the robustness loop (PR 7 built the detection
half — health detectors + flight recorder). Four layers, one subsystem
(docs/resilience.md):

- :mod:`trlx_tpu.resilience.chaos` — deterministic fault injection at
  named host-side sites (the ``--chaos-smoke`` self-check proves every
  recovery path below against injected failures);
- :mod:`trlx_tpu.utils.retry` — the transient-vs-permanent error
  taxonomy + bounded-backoff retry wrapped around checkpoint I/O,
  rollout-log writes, wandb emission, and server admission;
- :mod:`trlx_tpu.resilience.preemption` — SIGTERM/SIGINT → graceful
  drain at the next phase boundary (emergency atomic checkpoint +
  flight dump + distinct exit code);
- :mod:`trlx_tpu.resilience.supervisor` — ``train.resilience``-driven
  bounded auto-resume from the latest good checkpoint (imported lazily
  by `api.train`; import it as ``trlx_tpu.resilience.supervisor`` to
  avoid cycles with `utils/checkpoint.py`).

This package must stay import-light: `utils/checkpoint.py` imports
:mod:`.chaos` at module load.
"""

from trlx_tpu.resilience import chaos  # noqa: F401
from trlx_tpu.resilience.preemption import (  # noqa: F401
    PREEMPTION_EXIT_CODE,
    PreemptionDrain,
    PreemptionGuard,
    clear_request,
    drain_requested,
    install_guard,
    uninstall_guard,
)
