"""Deterministic fault injection at named host-side sites.

A recovery path that is never exercised is broken exactly when it is
needed — the ``--plant-nan`` / ``--health-smoke`` planted-anomaly
pattern, applied to failures themselves. Production code calls
:func:`check` at each *injection site*; with no specs configured the
call is a list-emptiness test (zero cost, always on). A configured spec
fires at its site — matched by ``(site, phase, step)`` so every
scenario is reproducible — for exactly ``count`` triggers, then goes
quiet, which is how a *transient* failure (fails twice, then the
filesystem recovers) is modeled deterministically.

Injection-site catalog (docs/resilience.md):

==================  ====================================================
site                where / what a spec injects
==================  ====================================================
``checkpoint.save`` `utils/checkpoint.py::save_checkpoint` — I/O error
                    before the orbax write (``error`` = transient OSError,
                    ``permanent`` = structure-mismatch ValueError)
``checkpoint.load`` `utils/checkpoint.py::load_checkpoint` — same modes
                    on the restore path
``writer.write``    the background JSONL writer's file append
                    (``disk_full`` = ENOSPC)
``engine.admit``    `inference/engine.py::submit` — admission failure on
                    the continuous rollout engine / inference server
``logger.emit``     `utils/logging.py` wandb emission
``preempt``         trainer phase boundary — delivers a real SIGTERM to
                    this process (the preemption drain then runs)
``slow_step``       trainer phase boundary — host-side ``stall`` of
                    ``delay_s`` seconds
==================  ====================================================

Specs come from :func:`configure` (the supervisor passes
``train.resilience.chaos`` through) or the ``TRLX_CHAOS`` environment
variable (a JSON list of spec dicts) so any entry point can be put
under chaos without code changes. Every firing is recorded in
:func:`events` for the ``--chaos-smoke`` self-check.
"""

from __future__ import annotations

import errno
import json
import os
import signal
import sys
import threading
import time
from dataclasses import dataclass, field, fields
from typing import Any, Dict, List, Optional, Sequence, Union

SITES = (
    "checkpoint.save",
    "checkpoint.load",
    "writer.write",
    "engine.admit",
    "logger.emit",
    "preempt",
    "slow_step",
)

MODES = ("error", "permanent", "disk_full", "preempt", "stall")

#: env var holding a JSON list of spec dicts, merged at configure time
ENV_VAR = "TRLX_CHAOS"


class ChaosInjectedIOError(OSError):
    """Injected transient I/O failure (classified transient by the
    `utils/retry.py` taxonomy via its OSError base, not by type)."""


class ChaosInjectedStructureError(ValueError):
    """Injected permanent failure; the message carries the orbax
    structure-mismatch phrasing so `utils/checkpoint.py` translates it
    exactly like the real thing."""


@dataclass
class ChaosSpec:
    """One scheduled injection.

    :param site: injection-site name (see :data:`SITES`).
    :param mode: ``error`` (transient OSError), ``permanent``
        (structure-mismatch ValueError), ``disk_full`` (ENOSPC),
        ``preempt`` (SIGTERM to this process), ``stall`` (host sleep of
        ``delay_s``).
    :param phase: fire only when the site reports this phase index
        (None = any phase).
    :param step: fire only at this step (None = any).
    :param count: total triggers before the spec goes quiet — a
        transient failure that recovers is ``count=2`` against a retry
        budget of 3+.
    :param delay_s: stall duration for ``mode="stall"``.
    """

    site: str
    mode: str = "error"
    phase: Optional[int] = None
    step: Optional[int] = None
    count: int = 1
    delay_s: float = 0.0
    remaining: int = field(init=False, default=0)

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(
                f"unknown chaos site {self.site!r}; known: {SITES}"
            )
        if self.mode not in MODES:
            raise ValueError(
                f"unknown chaos mode {self.mode!r}; known: {MODES}"
            )
        if self.count < 1:
            raise ValueError("chaos spec count must be >= 1")
        self.remaining = int(self.count)

    @classmethod
    def from_dict(cls, config: Dict[str, Any]) -> "ChaosSpec":
        known = {f.name for f in fields(cls) if f.init}
        unknown = set(config) - known
        if unknown:
            raise ValueError(
                f"Unknown chaos-spec keys: {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        return cls(**config)


class ChaosController:
    """Process-wide injection schedule; thread-safe (the writer thread
    and the train loop hit sites concurrently)."""

    def __init__(self):
        self._specs: List[ChaosSpec] = []
        self._events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    def configure(
        self, specs: Sequence[Union[ChaosSpec, Dict[str, Any]]]
    ) -> None:
        """Replace the schedule (and reset the event log) with ``specs``
        plus anything in :data:`ENV_VAR`."""
        parsed = [
            s if isinstance(s, ChaosSpec) else ChaosSpec.from_dict(s)
            for s in specs
        ]
        parsed += _env_specs()
        with self._lock:
            self._specs = parsed
            self._events = []

    def clear(self) -> None:
        with self._lock:
            self._specs = []
            self._events = []

    def active(self) -> bool:
        return bool(self._specs)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def check(
        self,
        site: str,
        *,
        phase: Optional[int] = None,
        step: Optional[int] = None,
    ) -> None:
        """Fire any matching armed spec (raising / signalling /
        stalling per its mode). The no-chaos fast path is one attribute
        read + truthiness test."""
        if not self._specs:
            return
        with self._lock:
            spec = self._match(site, phase, step)
            if spec is None:
                return
            spec.remaining -= 1
            self._events.append(
                {
                    "site": site,
                    "mode": spec.mode,
                    "phase": phase,
                    "step": step,
                    "remaining": spec.remaining,
                }
            )
        _fire(spec, site)

    def _match(
        self, site: str, phase: Optional[int], step: Optional[int]
    ) -> Optional[ChaosSpec]:
        for spec in self._specs:
            if spec.site != site or spec.remaining <= 0:
                continue
            if spec.phase is not None and spec.phase != phase:
                continue
            if spec.step is not None and spec.step != step:
                continue
            return spec
        return None


def _env_specs() -> List[ChaosSpec]:
    raw = os.environ.get(ENV_VAR, "")
    if not raw:
        return []
    try:
        entries = json.loads(raw)
    except json.JSONDecodeError as e:
        raise ValueError(
            f"{ENV_VAR} must be a JSON list of chaos-spec dicts: {e}"
        ) from e
    return [ChaosSpec.from_dict(d) for d in entries]


def _fire(spec: ChaosSpec, site: str) -> None:
    print(
        f"chaos: injecting {spec.mode!r} at site {site!r} "
        f"({spec.remaining} firings left)",
        file=sys.stderr,
    )
    if spec.mode == "error":
        raise ChaosInjectedIOError(
            errno.EIO, f"chaos: injected transient I/O error at {site}"
        )
    if spec.mode == "disk_full":
        raise ChaosInjectedIOError(
            errno.ENOSPC, f"chaos: injected disk-full at {site}"
        )
    if spec.mode == "permanent":
        raise ChaosInjectedStructureError(
            f"chaos: injected checkpoint structure mismatch at {site} "
            "(tree structures do not match)"
        )
    if spec.mode == "preempt":
        # a REAL signal, not a flag poke: the handler installed by
        # resilience/preemption.py (or the default die-now handler when
        # no guard is installed — also realistic) runs exactly as it
        # would under a scheduler-issued SIGTERM
        os.kill(os.getpid(), signal.SIGTERM)
        return
    if spec.mode == "stall":
        time.sleep(max(float(spec.delay_s), 0.0))


# ----------------------- module-level singleton ----------------------- #

_controller = ChaosController()


def configure(specs: Sequence[Union[ChaosSpec, Dict[str, Any]]]) -> None:
    _controller.configure(specs)


def clear() -> None:
    _controller.clear()


def active() -> bool:
    return _controller.active()


def events() -> List[Dict[str, Any]]:
    return _controller.events()


def check(
    site: str, *, phase: Optional[int] = None, step: Optional[int] = None
) -> None:
    _controller.check(site, phase=phase, step=step)
