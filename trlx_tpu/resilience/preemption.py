"""Preemption-safe shutdown: SIGTERM/SIGINT → drain at a phase boundary.

Preemptible TPU slices get a termination notice as a signal; the default
disposition kills the process mid-phase — mid-checkpoint-write in the
worst case. The guard here converts the signal into a *request* flag;
trainers poll it at phase boundaries (``BaseRLTrainer.maybe_drain``) and
drain gracefully: write an emergency atomic checkpoint + a
flight-recorder dump, then raise :class:`PreemptionDrain` (exit code
:data:`PREEMPTION_EXIT_CODE` — EX_TEMPFAIL, distinct from a crash so
schedulers can tell "resumable, was preempted" from "failed"). The
supervisor (`resilience/supervisor.py`) catches the drain and either
auto-resumes from the checkpoint it just wrote or re-raises, per
``train.resilience.resume_on_preemption``.

Signals are only interceptable on the main thread; installing from a
worker thread degrades to a warning (the run keeps its default
disposition — better than refusing to start).
"""

from __future__ import annotations

import signal
import sys
import threading
from typing import Dict, Optional, Sequence

#: BSD sysexits EX_TEMPFAIL: "try again later" — the semantically
#: honest code for a preempted-but-resumable run
PREEMPTION_EXIT_CODE = 75


class PreemptionDrain(Exception):
    """Raised by the trainer's phase-boundary drain after the emergency
    checkpoint committed. Carries the resume point."""

    exit_code = PREEMPTION_EXIT_CODE

    def __init__(
        self,
        message: str,
        step: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
    ):
        super().__init__(message)
        self.step = step
        self.checkpoint_dir = checkpoint_dir


class PreemptionGuard:
    """Installs handlers that record the signal instead of dying."""

    def __init__(self, signals: Sequence[str] = ("SIGTERM", "SIGINT")):
        self.signal_names = tuple(signals)
        self._signums = []
        for name in self.signal_names:
            num = getattr(signal, name, None)
            if num is None:
                raise ValueError(f"unknown signal name {name!r}")
            self._signums.append(num)
        self._previous: Dict[int, object] = {}
        self._received: Optional[int] = None
        self._announced = False
        self._installed = False

    # ----------------------------- handlers ---------------------------- #

    def _handler(self, signum, frame) -> None:
        # Async-signal-safe contract (engine 14, signal-unsafe-handler):
        # the handler runs between arbitrary bytecodes of the interrupted
        # thread, so it does EXACTLY one flag assignment — no print (the
        # interrupted thread may hold the stderr buffer lock), no
        # Signals() enum construction, no allocation-heavy calls. The
        # one-time announcement happens at the poll site instead.
        self._received = signum

    def _announce(self) -> None:
        """One-time stderr note, emitted from normal (poll-site) code —
        never from inside the handler."""
        if self._received is not None and not self._announced:
            self._announced = True
            print(
                f"resilience: received {signal.Signals(self._received).name}"
                " — will drain at the next phase boundary (emergency "
                "checkpoint + flight dump)",
                file=sys.stderr,
            )

    def install(self) -> bool:
        """Install handlers; returns False (with a warning) off the main
        thread, where CPython forbids signal.signal."""
        if self._installed:
            return True
        if threading.current_thread() is not threading.main_thread():
            print(
                "resilience: preemption guard skipped — signals can only "
                "be intercepted on the main thread",
                file=sys.stderr,
            )
            return False
        for num in self._signums:
            self._previous[num] = signal.signal(num, self._handler)
        self._installed = True
        return True

    def uninstall(self) -> None:
        if not self._installed:
            return
        for num, prev in self._previous.items():
            signal.signal(num, prev)
        self._previous.clear()
        self._installed = False

    def requested(self) -> bool:
        self._announce()
        return self._received is not None

    def clear(self) -> None:
        self._received = None
        self._announced = False

    @property
    def received_signal(self) -> Optional[str]:
        if self._received is None:
            return None
        return signal.Signals(self._received).name


# ----------------------- module-level singleton ----------------------- #

_guard: Optional[PreemptionGuard] = None


def install_guard(
    signals: Sequence[str] = ("SIGTERM", "SIGINT"),
) -> PreemptionGuard:
    """Install (or replace) the process guard; returns it."""
    global _guard
    if _guard is not None:
        _guard.uninstall()
    _guard = PreemptionGuard(signals)
    _guard.install()
    return _guard


def uninstall_guard() -> None:
    global _guard
    if _guard is not None:
        _guard.uninstall()
        _guard = None


def drain_requested() -> bool:
    """True when a guarded signal arrived and the run should drain at
    the next phase boundary. False (never raises) with no guard."""
    return _guard is not None and _guard.requested()


def clear_request() -> None:
    if _guard is not None:
        _guard.clear()


def received_signal() -> Optional[str]:
    return _guard.received_signal if _guard is not None else None
