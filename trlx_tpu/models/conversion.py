"""HF checkpoint -> JAX param-pytree conversion.

The reference consumes HF torch checkpoints directly
(``AutoModelForCausalLM.from_pretrained``, `ppo_models.py:233`;
``AutoModelForSeq2SeqLM`` bf16, `ppo_models.py:610-615`). The TPU framework
implements the architectures natively, so checkpoints are converted once,
host-side, into the flax param tree. Conversion is validated by exact-logit
parity tests against torch CPU forward (``tests/test_gpt2_parity.py``) —
SURVEY §7.3 lists this as a hard part.

GPT-2 note: HF ``Conv1D`` stores weights as (in_features, out_features),
identical to flax ``Dense`` kernels — no transposes anywhere in the GPT-2 map.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Mapping, Optional

import jax.numpy as jnp
import numpy as np

from trlx_tpu.models.gpt2 import GPT2Config


def _np(t) -> np.ndarray:
    """torch tensor / array-like -> numpy (host)."""
    if hasattr(t, "detach"):
        t = t.detach()
    if hasattr(t, "float"):
        # bf16 torch tensors can't go straight to numpy
        t = t.float()
    if hasattr(t, "cpu"):
        t = t.cpu()
    if hasattr(t, "numpy"):
        return t.numpy()
    return np.asarray(t)


def gpt2_config_from_hf(path_or_dict) -> GPT2Config:
    """Read an HF ``config.json`` (path or dict) into :class:`GPT2Config`."""
    if isinstance(path_or_dict, (str, os.PathLike)):
        with open(os.path.join(path_or_dict, "config.json")) as f:
            d = json.load(f)
    elif hasattr(path_or_dict, "to_dict"):
        d = path_or_dict.to_dict()
    else:
        d = dict(path_or_dict)
    return GPT2Config(
        vocab_size=d["vocab_size"],
        n_positions=d.get("n_positions", 1024),
        n_embd=d["n_embd"],
        n_layer=d["n_layer"],
        n_head=d["n_head"],
        layer_norm_epsilon=d.get("layer_norm_epsilon", 1e-5),
    )


def convert_gpt2_state_dict(
    state_dict: Mapping[str, Any], config: GPT2Config, dtype: str = "float32"
) -> Dict[str, Any]:
    """HF ``GPT2LMHeadModel`` state dict -> ``GPT2Model`` param tree.

    Accepts keys with or without the ``transformer.`` prefix. The LM head is
    tied to ``wte`` in both frameworks, so only the transformer is mapped.
    """
    sd = {k.removeprefix("transformer."): v for k, v in state_dict.items()}
    cast = lambda t: jnp.asarray(_np(t), dtype=jnp.dtype(dtype))

    params: Dict[str, Any] = {
        "wte": {"embedding": cast(sd["wte.weight"])},
        "wpe": {"embedding": cast(sd["wpe.weight"])},
        "ln_f": {"scale": cast(sd["ln_f.weight"]), "bias": cast(sd["ln_f.bias"])},
    }
    for i in range(config.n_layer):
        p = f"h.{i}."
        params[f"h_{i}"] = {
            "ln_1": {"scale": cast(sd[p + "ln_1.weight"]), "bias": cast(sd[p + "ln_1.bias"])},
            "ln_2": {"scale": cast(sd[p + "ln_2.weight"]), "bias": cast(sd[p + "ln_2.bias"])},
            "attn": {
                "c_attn": {
                    "kernel": cast(sd[p + "attn.c_attn.weight"]),
                    "bias": cast(sd[p + "attn.c_attn.bias"]),
                },
                "c_proj": {
                    "kernel": cast(sd[p + "attn.c_proj.weight"]),
                    "bias": cast(sd[p + "attn.c_proj.bias"]),
                },
            },
            "mlp": {
                "c_fc": {
                    "kernel": cast(sd[p + "mlp.c_fc.weight"]),
                    "bias": cast(sd[p + "mlp.c_fc.bias"]),
                },
                "c_proj": {
                    "kernel": cast(sd[p + "mlp.c_proj.weight"]),
                    "bias": cast(sd[p + "mlp.c_proj.bias"]),
                },
            },
        }
    return params


def load_gpt2_checkpoint(model_path: str, dtype: str = "float32"):
    """Load an on-disk HF GPT-2 checkpoint -> (GPT2Config, param tree).

    Uses torch only to deserialize weights (host-side); never touches the
    network (offline-safe).
    """
    from transformers import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(model_path, local_files_only=True)
    config = gpt2_config_from_hf(model.config)
    params = convert_gpt2_state_dict(model.state_dict(), config, dtype)
    return config, params
