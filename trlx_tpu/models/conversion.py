"""HF checkpoint -> JAX param-pytree conversion.

The reference consumes HF torch checkpoints directly
(``AutoModelForCausalLM.from_pretrained``, `ppo_models.py:233`;
``AutoModelForSeq2SeqLM`` bf16, `ppo_models.py:610-615`). The TPU framework
implements the architectures natively, so checkpoints are converted once,
host-side, into the flax param tree. Conversion is validated by exact-logit
parity tests against torch CPU forward (``tests/test_gpt2_parity.py``) —
SURVEY §7.3 lists this as a hard part.

GPT-2 note: HF ``Conv1D`` stores weights as (in_features, out_features),
identical to flax ``Dense`` kernels — no transposes anywhere in the GPT-2 map.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Mapping, Optional

import jax.numpy as jnp
import numpy as np

from trlx_tpu.models.gpt2 import GPT2Config


def _np(t) -> np.ndarray:
    """torch tensor / array-like -> numpy (host)."""
    if hasattr(t, "detach"):
        t = t.detach()
    if hasattr(t, "float"):
        # bf16 torch tensors can't go straight to numpy
        t = t.float()
    if hasattr(t, "cpu"):
        t = t.cpu()
    if hasattr(t, "numpy"):
        return t.numpy()
    return np.asarray(t)


def gpt2_config_from_hf(path_or_dict) -> GPT2Config:
    """Read an HF ``config.json`` (path or dict) into :class:`GPT2Config`."""
    if isinstance(path_or_dict, (str, os.PathLike)):
        with open(os.path.join(path_or_dict, "config.json")) as f:
            d = json.load(f)
    elif hasattr(path_or_dict, "to_dict"):
        d = path_or_dict.to_dict()
    else:
        d = dict(path_or_dict)
    return GPT2Config(
        vocab_size=d["vocab_size"],
        n_positions=d.get("n_positions", 1024),
        n_embd=d["n_embd"],
        n_layer=d["n_layer"],
        n_head=d["n_head"],
        layer_norm_epsilon=d.get("layer_norm_epsilon", 1e-5),
    )


def convert_gpt2_state_dict(
    state_dict: Mapping[str, Any], config: GPT2Config, dtype: str = "float32"
) -> Dict[str, Any]:
    """HF ``GPT2LMHeadModel`` state dict -> ``GPT2Model`` param tree.

    Accepts keys with or without the ``transformer.`` prefix. The LM head is
    tied to ``wte`` in both frameworks, so only the transformer is mapped.
    """
    sd = {k.removeprefix("transformer."): v for k, v in state_dict.items()}
    cast = lambda t: jnp.asarray(_np(t), dtype=jnp.dtype(dtype))

    params: Dict[str, Any] = {
        "wte": {"embedding": cast(sd["wte.weight"])},
        "wpe": {"embedding": cast(sd["wpe.weight"])},
        "ln_f": {"scale": cast(sd["ln_f.weight"]), "bias": cast(sd["ln_f.bias"])},
    }
    for i in range(config.n_layer):
        p = f"h.{i}."
        params[f"h_{i}"] = {
            "ln_1": {"scale": cast(sd[p + "ln_1.weight"]), "bias": cast(sd[p + "ln_1.bias"])},
            "ln_2": {"scale": cast(sd[p + "ln_2.weight"]), "bias": cast(sd[p + "ln_2.bias"])},
            "attn": {
                "c_attn": {
                    "kernel": cast(sd[p + "attn.c_attn.weight"]),
                    "bias": cast(sd[p + "attn.c_attn.bias"]),
                },
                "c_proj": {
                    "kernel": cast(sd[p + "attn.c_proj.weight"]),
                    "bias": cast(sd[p + "attn.c_proj.bias"]),
                },
            },
            "mlp": {
                "c_fc": {
                    "kernel": cast(sd[p + "mlp.c_fc.weight"]),
                    "bias": cast(sd[p + "mlp.c_fc.bias"]),
                },
                "c_proj": {
                    "kernel": cast(sd[p + "mlp.c_proj.weight"]),
                    "bias": cast(sd[p + "mlp.c_proj.bias"]),
                },
            },
        }
    return params


def t5_config_from_hf(path_or_dict) -> "T5Config":
    """Read an HF T5/UL2 ``config.json`` into :class:`T5Config`."""
    from trlx_tpu.models.t5 import T5Config

    if isinstance(path_or_dict, (str, os.PathLike)):
        with open(os.path.join(path_or_dict, "config.json")) as f:
            d = json.load(f)
    elif hasattr(path_or_dict, "to_dict"):
        d = path_or_dict.to_dict()
    else:
        d = dict(path_or_dict)
    return T5Config(
        vocab_size=d["vocab_size"],
        d_model=d["d_model"],
        d_kv=d["d_kv"],
        d_ff=d["d_ff"],
        num_layers=d["num_layers"],
        num_decoder_layers=d.get("num_decoder_layers", d["num_layers"]),
        num_heads=d["num_heads"],
        relative_attention_num_buckets=d.get("relative_attention_num_buckets", 32),
        relative_attention_max_distance=d.get("relative_attention_max_distance", 128),
        layer_norm_epsilon=d.get("layer_norm_epsilon", 1e-6),
        feed_forward_proj=d.get("feed_forward_proj", "relu"),
        tie_word_embeddings=d.get("tie_word_embeddings", True),
        decoder_start_token_id=d.get("decoder_start_token_id", 0) or 0,
    )


def convert_t5_state_dict(
    state_dict: Mapping[str, Any], config, dtype: str = "float32"
) -> Dict[str, Any]:
    """HF ``T5ForConditionalGeneration`` state dict -> ``T5Model`` param tree.

    torch ``nn.Linear`` stores (out, in); flax Dense wants (in, out) — every
    projection kernel transposes. HF parameterizes the relative attention
    bias inside block 0 of each stack and reuses it downstream; here it maps
    to the stack-level ``enc_rel_bias``/``dec_rel_bias`` modules.
    """
    sd = dict(state_dict)
    cast = lambda t: jnp.asarray(_np(t), dtype=jnp.dtype(dtype))
    castT = lambda t: jnp.asarray(_np(t).T.copy(), dtype=jnp.dtype(dtype))

    def attn(prefix: str) -> Dict[str, Any]:
        return {
            "q": {"kernel": castT(sd[prefix + ".q.weight"])},
            "k": {"kernel": castT(sd[prefix + ".k.weight"])},
            "v": {"kernel": castT(sd[prefix + ".v.weight"])},
            "o": {"kernel": castT(sd[prefix + ".o.weight"])},
        }

    def ff(prefix: str) -> Dict[str, Any]:
        if config.is_gated_act:
            return {
                "wi_0": {"kernel": castT(sd[prefix + ".wi_0.weight"])},
                "wi_1": {"kernel": castT(sd[prefix + ".wi_1.weight"])},
                "wo": {"kernel": castT(sd[prefix + ".wo.weight"])},
            }
        return {
            "wi": {"kernel": castT(sd[prefix + ".wi.weight"])},
            "wo": {"kernel": castT(sd[prefix + ".wo.weight"])},
        }

    params: Dict[str, Any] = {
        "shared": {"embedding": cast(sd["shared.weight"])},
        "enc_rel_bias": {
            "relative_attention_bias": {
                "embedding": cast(
                    sd["encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"]
                )
            }
        },
        "dec_rel_bias": {
            "relative_attention_bias": {
                "embedding": cast(
                    sd["decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"]
                )
            }
        },
        "enc_final_ln": {"weight": cast(sd["encoder.final_layer_norm.weight"])},
        "dec_final_ln": {"weight": cast(sd["decoder.final_layer_norm.weight"])},
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = {"kernel": castT(sd["lm_head.weight"])}

    for i in range(config.num_layers):
        p = f"encoder.block.{i}."
        params[f"enc_{i}"] = {
            "SelfAttention": attn(p + "layer.0.SelfAttention"),
            "ln_self": {"weight": cast(sd[p + "layer.0.layer_norm.weight"])},
            "DenseReluDense": ff(p + "layer.1.DenseReluDense"),
            "ln_ff": {"weight": cast(sd[p + "layer.1.layer_norm.weight"])},
        }
    for i in range(config.num_decoder_layers):
        p = f"decoder.block.{i}."
        params[f"dec_{i}"] = {
            "SelfAttention": attn(p + "layer.0.SelfAttention"),
            "ln_self": {"weight": cast(sd[p + "layer.0.layer_norm.weight"])},
            "EncDecAttention": attn(p + "layer.1.EncDecAttention"),
            "ln_cross": {"weight": cast(sd[p + "layer.1.layer_norm.weight"])},
            "DenseReluDense": ff(p + "layer.2.DenseReluDense"),
            "ln_ff": {"weight": cast(sd[p + "layer.2.layer_norm.weight"])},
        }
    return params


def load_t5_checkpoint(model_path: str, dtype: str = "float32"):
    """Load an on-disk HF T5/UL2 checkpoint -> (T5Config, param tree).

    The fork loads its checkpoint in bf16 (`ppo_models.py:610-615`); here
    param dtype is configurable (bf16 compute is set by the arch config).
    """
    from transformers import AutoModelForSeq2SeqLM

    model = AutoModelForSeq2SeqLM.from_pretrained(model_path, local_files_only=True)
    config = t5_config_from_hf(model.config)
    params = convert_t5_state_dict(model.state_dict(), config, dtype)
    return config, params


def gptj_config_from_hf(path_or_dict) -> "GPTJConfig":
    from trlx_tpu.models.gptj import GPTJConfig

    if isinstance(path_or_dict, (str, os.PathLike)):
        with open(os.path.join(path_or_dict, "config.json")) as f:
            d = json.load(f)
    elif hasattr(path_or_dict, "to_dict"):
        d = path_or_dict.to_dict()
    else:
        d = dict(path_or_dict)
    return GPTJConfig(
        vocab_size=d["vocab_size"],
        n_positions=d.get("n_positions", 2048),
        n_embd=d["n_embd"],
        n_layer=d["n_layer"],
        n_head=d["n_head"],
        rotary_dim=d.get("rotary_dim") or (d["n_embd"] // d["n_head"]),
        layer_norm_epsilon=d.get("layer_norm_epsilon", 1e-5),
    )


def convert_gptj_state_dict(
    state_dict: Mapping[str, Any], config, dtype: str = "float32"
) -> Dict[str, Any]:
    """HF ``GPTJForCausalLM`` -> ``GPTJModel`` params (Linear kernels
    transpose; lm_head is untied with bias)."""
    sd = {k.removeprefix("transformer."): v for k, v in state_dict.items()}
    cast = lambda t: jnp.asarray(_np(t), dtype=jnp.dtype(dtype))
    castT = lambda t: jnp.asarray(_np(t).T.copy(), dtype=jnp.dtype(dtype))

    params: Dict[str, Any] = {
        "wte": {"embedding": cast(sd["wte.weight"])},
        "ln_f": {"scale": cast(sd["ln_f.weight"]), "bias": cast(sd["ln_f.bias"])},
        "lm_head": {
            "kernel": castT(sd["lm_head.weight"]),
            "bias": cast(sd["lm_head.bias"]),
        },
    }
    for i in range(config.n_layer):
        p = f"h.{i}."
        params[f"h_{i}"] = {
            "ln_1": {"scale": cast(sd[p + "ln_1.weight"]), "bias": cast(sd[p + "ln_1.bias"])},
            "attn": {
                "q_proj": {"kernel": castT(sd[p + "attn.q_proj.weight"])},
                "k_proj": {"kernel": castT(sd[p + "attn.k_proj.weight"])},
                "v_proj": {"kernel": castT(sd[p + "attn.v_proj.weight"])},
                "out_proj": {"kernel": castT(sd[p + "attn.out_proj.weight"])},
            },
            "mlp": {
                "fc_in": {
                    "kernel": castT(sd[p + "mlp.fc_in.weight"]),
                    "bias": cast(sd[p + "mlp.fc_in.bias"]),
                },
                "fc_out": {
                    "kernel": castT(sd[p + "mlp.fc_out.weight"]),
                    "bias": cast(sd[p + "mlp.fc_out.bias"]),
                },
            },
        }
    return params


def load_gptj_checkpoint(model_path: str, dtype: str = "float32"):
    from transformers import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(model_path, local_files_only=True)
    config = gptj_config_from_hf(model.config)
    return config, convert_gptj_state_dict(model.state_dict(), config, dtype)


def neox_config_from_hf(path_or_dict) -> "NeoXConfig":
    from trlx_tpu.models.neox import NeoXConfig

    if isinstance(path_or_dict, (str, os.PathLike)):
        with open(os.path.join(path_or_dict, "config.json")) as f:
            d = json.load(f)
    elif hasattr(path_or_dict, "to_dict"):
        d = path_or_dict.to_dict()
    else:
        d = dict(path_or_dict)
    return NeoXConfig(
        vocab_size=d["vocab_size"],
        max_position_embeddings=d.get("max_position_embeddings", 2048),
        hidden_size=d["hidden_size"],
        num_hidden_layers=d["num_hidden_layers"],
        num_attention_heads=d["num_attention_heads"],
        rotary_pct=d.get("rotary_pct", 0.25),
        rotary_emb_base=d.get("rotary_emb_base", 10000.0),
        use_parallel_residual=d.get("use_parallel_residual", True),
        layer_norm_eps=d.get("layer_norm_eps", 1e-5),
    )


def convert_neox_state_dict(
    state_dict: Mapping[str, Any], config, dtype: str = "float32"
) -> Dict[str, Any]:
    """HF ``GPTNeoXForCausalLM`` -> ``NeoXModel`` params. The fused QKV
    kernel keeps HF's head-major [H, 3*Dh] output layout (transpose only)."""
    sd = {k.removeprefix("gpt_neox."): v for k, v in state_dict.items()}
    cast = lambda t: jnp.asarray(_np(t), dtype=jnp.dtype(dtype))
    castT = lambda t: jnp.asarray(_np(t).T.copy(), dtype=jnp.dtype(dtype))

    params: Dict[str, Any] = {
        "wte": {"embedding": cast(sd["embed_in.weight"])},
        "ln_f": {
            "scale": cast(sd["final_layer_norm.weight"]),
            "bias": cast(sd["final_layer_norm.bias"]),
        },
        "lm_head": {"kernel": castT(sd["embed_out.weight"])},
    }
    for i in range(config.num_hidden_layers):
        p = f"layers.{i}."
        params[f"h_{i}"] = {
            "ln_1": {
                "scale": cast(sd[p + "input_layernorm.weight"]),
                "bias": cast(sd[p + "input_layernorm.bias"]),
            },
            "ln_2": {
                "scale": cast(sd[p + "post_attention_layernorm.weight"]),
                "bias": cast(sd[p + "post_attention_layernorm.bias"]),
            },
            "attn": {
                "query_key_value": {
                    "kernel": castT(sd[p + "attention.query_key_value.weight"]),
                    "bias": cast(sd[p + "attention.query_key_value.bias"]),
                },
                "dense": {
                    "kernel": castT(sd[p + "attention.dense.weight"]),
                    "bias": cast(sd[p + "attention.dense.bias"]),
                },
            },
            "mlp": {
                "dense_h_to_4h": {
                    "kernel": castT(sd[p + "mlp.dense_h_to_4h.weight"]),
                    "bias": cast(sd[p + "mlp.dense_h_to_4h.bias"]),
                },
                "dense_4h_to_h": {
                    "kernel": castT(sd[p + "mlp.dense_4h_to_h.weight"]),
                    "bias": cast(sd[p + "mlp.dense_4h_to_h.bias"]),
                },
            },
        }
    return params


def load_neox_checkpoint(model_path: str, dtype: str = "float32"):
    from transformers import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(model_path, local_files_only=True)
    config = neox_config_from_hf(model.config)
    return config, convert_neox_state_dict(model.state_dict(), config, dtype)


def gpt_neo_config_from_hf(path_or_dict) -> "GPTNeoConfig":
    from trlx_tpu.models.gpt_neo import GPTNeoConfig, expand_attention_types

    if isinstance(path_or_dict, (str, os.PathLike)):
        with open(os.path.join(path_or_dict, "config.json")) as f:
            d = json.load(f)
    elif hasattr(path_or_dict, "to_dict"):
        d = path_or_dict.to_dict()
    else:
        d = dict(path_or_dict)
    return GPTNeoConfig(
        vocab_size=d["vocab_size"],
        max_position_embeddings=d.get("max_position_embeddings", 2048),
        hidden_size=d["hidden_size"],
        num_layers=d["num_layers"],
        num_heads=d["num_heads"],
        intermediate_size=d.get("intermediate_size"),
        window_size=d.get("window_size", 256),
        attention_layers=expand_attention_types(
            d.get("attention_types") or [], d["num_layers"]
        ),
        layer_norm_epsilon=d.get("layer_norm_epsilon", 1e-5),
    )


def convert_gpt_neo_state_dict(
    state_dict: Mapping[str, Any], config, dtype: str = "float32"
) -> Dict[str, Any]:
    """HF ``GPTNeoForCausalLM`` -> ``GPTNeoModel`` params.

    GPT-Neo uses torch ``nn.Linear`` everywhere (kernels transpose, unlike
    GPT-2's Conv1D); q/k/v are bias-free, ``out_proj`` and MLP carry biases;
    the LM head is tied to ``wte``.
    """
    sd = {k.removeprefix("transformer."): v for k, v in state_dict.items()}
    cast = lambda t: jnp.asarray(_np(t), dtype=jnp.dtype(dtype))
    castT = lambda t: jnp.asarray(_np(t).T.copy(), dtype=jnp.dtype(dtype))

    params: Dict[str, Any] = {
        "wte": {"embedding": cast(sd["wte.weight"])},
        "wpe": {"embedding": cast(sd["wpe.weight"])},
        "ln_f": {"scale": cast(sd["ln_f.weight"]), "bias": cast(sd["ln_f.bias"])},
    }
    for i in range(config.num_layers):
        p = f"h.{i}."
        a = p + "attn.attention."
        params[f"h_{i}"] = {
            "ln_1": {"scale": cast(sd[p + "ln_1.weight"]), "bias": cast(sd[p + "ln_1.bias"])},
            "ln_2": {"scale": cast(sd[p + "ln_2.weight"]), "bias": cast(sd[p + "ln_2.bias"])},
            "attn": {
                "q_proj": {"kernel": castT(sd[a + "q_proj.weight"])},
                "k_proj": {"kernel": castT(sd[a + "k_proj.weight"])},
                "v_proj": {"kernel": castT(sd[a + "v_proj.weight"])},
                "out_proj": {
                    "kernel": castT(sd[a + "out_proj.weight"]),
                    "bias": cast(sd[a + "out_proj.bias"]),
                },
            },
            "mlp": {
                "c_fc": {
                    "kernel": castT(sd[p + "mlp.c_fc.weight"]),
                    "bias": cast(sd[p + "mlp.c_fc.bias"]),
                },
                "c_proj": {
                    "kernel": castT(sd[p + "mlp.c_proj.weight"]),
                    "bias": cast(sd[p + "mlp.c_proj.bias"]),
                },
            },
        }
    return params


def load_gpt_neo_checkpoint(model_path: str, dtype: str = "float32"):
    from transformers import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(model_path, local_files_only=True)
    config = gpt_neo_config_from_hf(model.config)
    return config, convert_gpt_neo_state_dict(model.state_dict(), config, dtype)


def load_gpt2_checkpoint(model_path: str, dtype: str = "float32"):
    """Load an on-disk HF GPT-2 checkpoint -> (GPT2Config, param tree).

    Uses torch only to deserialize weights (host-side); never touches the
    network (offline-safe).
    """
    from transformers import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(model_path, local_files_only=True)
    config = gpt2_config_from_hf(model.config)
    params = convert_gpt2_state_dict(model.state_dict(), config, dtype)
    return config, params
