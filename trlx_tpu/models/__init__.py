"""Model families (reference layer 2, ``trlx/model/nn/``).

Each family provides: a frozen arch config, a flax backbone with explicit
KV-cache decode support, TP partition rules, and an HF-checkpoint converter.
"""

from trlx_tpu.models.gpt2 import GPT2Config, GPT2Model, init_cache
from trlx_tpu.models.heads import (
    CausalLMWithILQLHeads,
    CausalLMWithValueHead,
    ILQLHeads,
    MLPHead,
)

__all__ = [
    "GPT2Config",
    "GPT2Model",
    "init_cache",
    "CausalLMWithValueHead",
    "CausalLMWithILQLHeads",
    "ILQLHeads",
    "MLPHead",
]
