"""GPT-2 Switch-MoE family: expert parallelism integrated into a real
trainable policy.

The reference has no MoE at all (SURVEY §2.9: expert parallel "NO"); round
1 shipped `parallel/moe.py` only as a standalone primitive. This family
makes the ``ep`` mesh axis a *training* capability: every ``moe_every``-th
transformer block replaces its dense MLP with a top-1 switch layer whose
experts shard over ``ep`` — dispatch/return ride two ``all_to_all``
collectives per layer (`parallel/moe.py`), composed with dp/fsdp on the
same mesh.

Two numerically-matching execution paths, chosen by the installed ep mesh:
- **dense** (no ``ep`` axis, decode, CPU tests): every expert computes all
  tokens; the one-hot gate selects — exact switch semantics with no
  capacity drops, affordable at small E and single-token decode;
- **sharded** (``ep`` > 1): `moe_apply`'s static-shape dispatch with
  per-device expert capacity ``ceil(capacity_factor · n_local / E)``.
  With ``capacity_factor >= n_experts`` nothing drops and the two paths
  agree exactly (`tests/test_moe_integration.py`).

The mesh is process state, not config (a ``Mesh`` can't live in a frozen
flax module): trainers install it via :func:`set_ep_mesh` before tracing;
``None`` (the default) keeps every forward on the dense path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from trlx_tpu.models.gpt2 import Attention, GPT2Model, PARTITION_RULES

_EP_MESH: Optional[Mesh] = None


def set_ep_mesh(mesh: Optional[Mesh]) -> None:
    """Install (or clear) the mesh whose ``ep`` axis shards switch experts.
    Takes effect at trace time — call before building jitted programs."""
    global _EP_MESH
    _EP_MESH = mesh if mesh is not None and dict(mesh.shape).get("ep", 1) > 1 else None


def get_ep_mesh() -> Optional[Mesh]:
    """The currently-installed ep mesh context (None = dense path)."""
    return _EP_MESH


def reset() -> None:
    """Clear the module-global ep mesh context.

    The context is process state (see module docstring): an MoE trainer
    installs it and nothing ever uninstalls it, so a later *non*-MoE
    trace in the same process can silently re-enter the sharded expert
    path on a stale mesh. Test suites must call this between tests
    (``tests/conftest.py`` does, autouse); long-lived training processes
    that build successive trainers should call it when a trainer is
    discarded."""
    set_ep_mesh(None)


@dataclass
class GPT2MoEConfig:
    """GPT-2 arch + switch-MoE knobs. Deliberately not a GPT2Config
    subclass: the pp runner and HF converters key on exact GPT2Config."""

    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    layer_norm_epsilon: float = 1e-5
    n_experts: int = 4
    moe_every: int = 2  # blocks 1, 1+k, ... use the switch MLP
    capacity_factor: float = 2.0
    # load-balancing regularizers (training only; see SwitchMLP): the
    # Switch-Transformer auxiliary loss (α, paper §2.2 uses 0.01) keeps
    # top-1 routing from collapsing onto few experts once capacity drops
    # are real, and the ST-MoE router z-loss bounds router logit growth
    router_aux_coef: float = 0.01
    router_z_coef: float = 0.001
    dtype: str = "bfloat16"
    param_dtype: str = "float32"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "GPT2MoEConfig":
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in d.items() if k in known})


class SwitchMLP(nn.Module):
    """Top-1 switch MLP (router + E gelu experts), gate-weighted output.
    Residual stays outside (in the block), as switch layers require —
    over-capacity tokens on the sharded path contribute zero."""

    config: GPT2MoEConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:  # [B, T, D]
        cfg = self.config
        D, F, E = cfg.n_embd, 4 * cfg.n_embd, cfg.n_experts
        dtype = jnp.dtype(cfg.dtype)
        pdtype = jnp.dtype(cfg.param_dtype)
        init = nn.initializers.normal(0.02)
        router = self.param("router", init, (D, E), pdtype)
        wi = self.param("wi", init, (E, D, F), pdtype)
        bi = self.param("bi", nn.initializers.zeros, (E, F), pdtype)
        wo = self.param("wo", init, (E, F, D), pdtype)
        bo = self.param("bo", nn.initializers.zeros, (E, D), pdtype)

        shape = x.shape
        toks = x.reshape(-1, D).astype(dtype)

        # Router pass over the full token set, in float32 (near-tied logits
        # must argmax identically to the sharded twin of this layer).
        logits = toks.astype(jnp.float32) @ router.astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)  # [N, E]
        expert = jnp.argmax(probs, axis=-1)  # [N]

        # Load-balancing regularizers, sown for the trainer's loss when it
        # opens the "moe_losses" collection (training forwards only; the
        # sampler applies immutably, where sow is a no-op):
        # - Switch-Transformer aux loss (§2.2): E · Σ_e f_e·P_e, with f_e
        #   the fraction of tokens argmax-routed to expert e (no gradient,
        #   as the paper prescribes) and P_e the mean router probability
        #   (carries the gradient). Uniform routing gives the minimum 1.
        # - ST-MoE router z-loss: mean(logsumexp(logits)²) bounds logit
        #   growth, keeping the f32 softmax sharp but stable.
        # - max_load: busiest expert's token fraction (diagnostic; 1/E is
        #   perfect balance, ~1 is router collapse).
        if self.is_mutable_collection("moe_losses"):
            frac = jnp.mean(
                jax.nn.one_hot(expert, E, dtype=jnp.float32), axis=0
            )
            pmean = jnp.mean(probs, axis=0)
            self.sow("moe_losses", "aux_loss", E * jnp.sum(frac * pmean))
            self.sow(
                "moe_losses", "router_z",
                jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2),
            )
            self.sow("moe_losses", "max_load", jnp.max(frac))

        # Single-token forwards (T == 1, a static trace-time property —
        # exactly the sampler's decode steps) always take the dense path:
        # per-step token count is only B, so sharded per-device expert
        # capacity ceil(cf·B/(dp·fsdp·ep)/E) rounds to ~1 and routing
        # imbalance would silently zero dropped tokens' MLP output
        # mid-rollout; dense at B tokens is cheap and exact.
        mesh = _EP_MESH if shape[1] > 1 else None
        if mesh is not None:
            from trlx_tpu.parallel.moe import moe_apply

            def expert_fn(p, t):
                h = nn.gelu(t @ p["wi"] + p["bi"], approximate=True)
                return h @ p["wo"] + p["bo"]

            stacked = {
                "wi": wi.astype(dtype), "bi": bi.astype(dtype),
                "wo": wo.astype(dtype), "bo": bo.astype(dtype),
            }
            y = moe_apply(
                expert_fn, stacked, toks, router.astype(jnp.float32),
                mesh, capacity_factor=cfg.capacity_factor,
                batch_axes=("dp", "fsdp"),
            )
        else:
            gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)
            h = jnp.einsum("nd,edf->enf", toks, wi.astype(dtype))
            h = nn.gelu(h + bi.astype(dtype)[:, None], approximate=True)
            out_e = jnp.einsum("enf,efd->end", h, wo.astype(dtype))
            out_e = out_e + bo.astype(dtype)[:, None]
            sel = jax.nn.one_hot(expert, E, dtype=jnp.float32) * gate  # [N, E]
            y = jnp.einsum("end,ne->nd", out_e.astype(jnp.float32), sel)
        return y.reshape(shape).astype(dtype)


class MoEBlock(nn.Module):
    """`gpt2.Block` with the dense MLP swapped for :class:`SwitchMLP`."""

    config: GPT2MoEConfig

    @nn.compact
    def __call__(self, x, bias, cache_kv=None, cache_index=None, causal=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        eps = cfg.layer_norm_epsilon
        h = nn.LayerNorm(epsilon=eps, dtype=dtype, name="ln_1")(x)
        attn_out, new_kv = Attention(cfg, name="attn")(
            h, bias, cache_kv, cache_index, causal
        )
        x = x + attn_out
        h = nn.LayerNorm(epsilon=eps, dtype=dtype, name="ln_2")(x)
        x = x + SwitchMLP(cfg, name="mlp")(h)
        return x, new_kv


class GPT2MoEModel(GPT2Model):
    """GPT-2 trunk with switch-MoE MLPs every ``moe_every``-th block
    (starting at block 1 so block 0 stays dense, as switch transformers
    interleave). Shares `GPT2Model`'s embed/logits/call interface — the
    samplers, hydra hooks, and trainers work unchanged."""

    config: GPT2MoEConfig

    def setup(self):
        cfg = self.config
        pdtype = jnp.dtype(cfg.param_dtype)
        self.wte = nn.Embed(cfg.vocab_size, cfg.n_embd, param_dtype=pdtype, name="wte")
        self.wpe = nn.Embed(cfg.n_positions, cfg.n_embd, param_dtype=pdtype, name="wpe")
        from trlx_tpu.models.gpt2 import Block

        # MoE at blocks moe_every-1, 2*moe_every-1, ... (moe_every=1 =>
        # every block; =2 => alternating with block 0 dense)
        is_moe = [
            i % cfg.moe_every == cfg.moe_every - 1 for i in range(cfg.n_layer)
        ]
        if not any(is_moe):
            raise ValueError(
                f"gpt2_moe with n_layer={cfg.n_layer}, "
                f"moe_every={cfg.moe_every} has no MoE blocks — an ep mesh "
                "axis would have no experts to shard; lower moe_every or "
                "use the dense gpt2 family"
            )
        self.h = [
            (MoEBlock if is_moe[i] else Block)(cfg, name=f"h_{i}")
            for i in range(cfg.n_layer)
        ]
        self.ln_f = nn.LayerNorm(
            epsilon=cfg.layer_norm_epsilon, dtype=jnp.dtype(cfg.dtype), name="ln_f"
        )


# experts live stacked on a leading [E] axis sharded over ep; dense blocks
# keep the gpt2 tp rules
GPT2_MOE_PARTITION_RULES = list(PARTITION_RULES) + [
    (r"mlp/router", P(None, None)),
    (r"mlp/wi", P("ep", None, None)),
    (r"mlp/bi", P("ep", None)),
    (r"mlp/wo", P("ep", None, None)),
    (r"mlp/bo", P("ep", None)),
]


def moe_loss_summary(collection) -> Dict[str, jax.Array]:
    """Aggregate a ``moe_losses`` sow collection (one entry per MoE block)
    into scalars: mean ``aux_loss`` / ``router_z`` across layers, max
    ``max_load`` across layers. Used by trainers to add the balance
    penalty to the training loss and to surface routing health in stats."""
    buckets: Dict[str, list] = {"aux_loss": [], "router_z": [], "max_load": []}

    def walk(node):
        if isinstance(node, dict):
            for k, v in node.items():
                if k in buckets:
                    buckets[k].extend(v)  # sow stores a tuple per call
                else:
                    walk(v)

    walk(collection)
    if not buckets["aux_loss"]:
        raise ValueError("no MoE losses were sown — is this an MoE model?")
    return {
        "aux_loss": jnp.mean(jnp.stack(buckets["aux_loss"])),
        "router_z": jnp.mean(jnp.stack(buckets["router_z"])),
        "max_load": jnp.max(jnp.stack(buckets["max_load"])),
    }


def apply_router_penalty(loss, stats, moe: Dict[str, jax.Array], cfg):
    """Add the router load-balancing penalty to a training loss and surface
    the routing health in the step stats — shared by every trainer that
    trains an MoE family (PPO and ILQL use identical objectives here)."""
    penalty = (
        cfg.router_aux_coef * moe["aux_loss"]
        + cfg.router_z_coef * moe["router_z"]
    )
    stats = dict(
        stats,
        **{
            "losses/total_loss": stats["losses/total_loss"] + penalty,
            "losses/moe_aux": moe["aux_loss"],
            "losses/router_z": moe["router_z"],
            "moe/max_load": moe["max_load"],
        },
    )
    return loss + penalty, stats


def _no_checkpoint(path: str, dtype: str = "float32"):
    raise ValueError(
        "gpt2_moe has no HF checkpoint counterpart; train from scratch "
        "(model_arch) or convert a dense GPT-2 and grow experts offline"
    )
