"""GPT-Neo causal LM, written TPU-first in flax.linen.

Completes the reference's supported causal-LM families ("gpt2, gpt-j,
gpt-neo, gpt-neox up to 20B" — reference ``README.md:6``,
``docs/source/index.rst:8-9``); the reference gets the architecture from HF
torch via ``AutoModelForCausalLM`` (``ilql_models.py:187``,
``ppo_models.py:233``). Architecture deltas vs GPT-2:

- separate bias-free q/k/v projections, ``out_proj`` with bias;
- **unscaled** attention logits (no 1/sqrt(d); folded into init by EleutherAI)
  — implemented by pre-multiplying q by sqrt(d) to cancel the shared
  attention core's scale, as T5 does;
- alternating global / local (sliding-window, default 256) attention layers
  per ``attention_types``; local layers use an explicit band bias;
- MLP ``c_fc``/``c_proj`` are torch ``nn.Linear`` (kernels transpose on
  conversion, unlike GPT-2's Conv1D);
- tied LM head, learned position embeddings.

Same call interface as ``GPT2Model`` (incl. hydra ``start_layer`` /
``capture_hidden_at`` hooks and the explicit KV cache).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from trlx_tpu.ops.attention import (
    NEG_INF,
    causal_dispatch,
    combine_biases,
    dot_product_attention,
    padding_bias,
)


def expand_attention_types(attention_types, n_layer: int) -> Tuple[str, ...]:
    """HF ``[[["global", "local"], 12]]`` -> per-layer type tuple."""
    if not attention_types:
        return tuple("global" for _ in range(n_layer))
    layers: List[str] = []
    for pattern, repeat in attention_types:
        layers.extend(list(pattern) * repeat)
    if len(layers) != n_layer:
        raise ValueError(
            f"attention_types expands to {len(layers)} layers, expected {n_layer}"
        )
    return tuple(layers)


@dataclass(frozen=True)
class GPTNeoConfig:
    """Architecture hyperparameters (HF ``GPTNeoConfig`` field names)."""

    vocab_size: int = 50257
    max_position_embeddings: int = 2048
    hidden_size: int = 2048
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: Optional[int] = None  # None -> 4 * hidden
    window_size: int = 256
    attention_layers: Tuple[str, ...] = ()  # per-layer "global"/"local"; () -> all global
    layer_norm_epsilon: float = 1e-5
    dtype: str = "bfloat16"
    param_dtype: str = "float32"
    # rollout KV-cache storage ("bfloat16" | "int8" | "auto"); see
    # models/gpt2.py::write_cache — decode is HBM-bound and the
    # cache is its dominant traffic, int8 halves it
    kv_cache_dtype: str = "bfloat16"

    def __post_init__(self):
        from trlx_tpu.models.gpt2 import validate_kv_cache_dtype

        validate_kv_cache_dtype(self.kv_cache_dtype)

    @property
    def layer_types(self) -> Tuple[str, ...]:
        if self.attention_layers:
            return self.attention_layers
        return tuple("global" for _ in range(self.num_layers))

    @property
    def inner_dim(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "GPTNeoConfig":
        d = dict(d)
        if "attention_types" in d and "attention_layers" not in d:
            d["attention_layers"] = expand_attention_types(
                d.pop("attention_types"), d.get("num_layers", cls.num_layers)
            )
        if isinstance(d.get("attention_layers"), list):
            d["attention_layers"] = tuple(d["attention_layers"])
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


GPT_NEO_PARTITION_RULES = [
    (r"wte/embedding", P(None, "tp")),
    (r"attn/(q_proj|k_proj|v_proj)/kernel", P(None, "tp")),
    (r"attn/out_proj/kernel", P("tp", None)),
    (r"mlp/c_fc/kernel", P(None, "tp")),
    (r"mlp/c_proj/kernel", P("tp", None)),
]


def local_causal_bias(
    q_len: int,
    kv_len: int,
    window: int,
    offset=0,
    dtype=jnp.float32,
) -> jax.Array:
    """[1, 1, Q, K] band bias: j <= i and i - j < window (sliding window).

    Matches HF GPT-Neo local attention: each query sees at most ``window``
    most recent positions including itself. A [B]-vector ``offset`` (rows
    decoding at different cache depths — the continuous-batching
    engine's per-row ``cache_index``) yields a [B, 1, Q, K] bias, the
    same contract as ``ops/attention.py::causal_bias`` — without this
    branch the slot-admission engine could not serve local-attention
    GPT-Neo configs at all.
    """
    off = jnp.asarray(offset)
    k_pos = jnp.arange(kv_len)[None, :]
    if off.ndim:
        q_pos = (
            jnp.arange(q_len)[None, :, None]
            + off.astype(jnp.int32)[:, None, None]
        )  # [B, Q, 1]
        kb = k_pos[None, :, :]
        visible = (kb <= q_pos) & (q_pos - kb < window)
        return jnp.where(visible, 0.0, NEG_INF).astype(dtype)[:, None, :, :]
    q_pos = jnp.arange(q_len)[:, None] + off
    visible = (k_pos <= q_pos) & (q_pos - k_pos < window)
    return jnp.where(visible, 0.0, NEG_INF).astype(dtype)[None, None, :, :]


class GPTNeoAttention(nn.Module):
    """Windowing is decided by the caller: local layers receive an explicit
    band bias, global layers the shared causal flag/bias — the module itself
    is type-agnostic."""

    config: GPTNeoConfig

    @nn.compact
    def __call__(self, x, bias, cache_kv=None, cache_index=None, causal=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        pdtype = jnp.dtype(cfg.param_dtype)
        B, T, D = x.shape
        head_dim = cfg.hidden_size // cfg.num_heads

        proj = lambda name, use_bias: nn.Dense(
            cfg.hidden_size, use_bias=use_bias, dtype=dtype,
            param_dtype=pdtype, name=name,
        )
        q = proj("q_proj", False)(x).reshape(B, T, cfg.num_heads, head_dim)
        k = proj("k_proj", False)(x).reshape(B, T, cfg.num_heads, head_dim)
        v = proj("v_proj", False)(x).reshape(B, T, cfg.num_heads, head_dim)

        new_kv = None
        if cache_kv is not None:
            from trlx_tpu.models.gpt2 import write_cache

            # bias width == attention view width (a prompt-only mask —
            # the chunked prefill — narrows the cache view to match)
            view_len = bias.shape[-1] if bias is not None else None
            k, v, new_kv = write_cache(
                cache_kv, k, v, cache_index, dtype, view_len=view_len
            )

        # GPT-Neo does not scale attention logits; cancel the shared core's
        # 1/sqrt(d) (HF computes q @ k^T directly in float32).
        q = q * jnp.asarray(head_dim, q.dtype) ** 0.5
        out = dot_product_attention(q, k, v, bias, causal=causal)
        out = out.reshape(B, T, cfg.hidden_size)
        return proj("out_proj", True)(out), new_kv


class GPTNeoMLP(nn.Module):
    config: GPTNeoConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        pdtype = jnp.dtype(cfg.param_dtype)
        h = nn.Dense(cfg.inner_dim, dtype=dtype, param_dtype=pdtype, name="c_fc")(x)
        h = nn.gelu(h, approximate=True)  # gelu_new
        return nn.Dense(cfg.hidden_size, dtype=dtype, param_dtype=pdtype, name="c_proj")(h)


class GPTNeoBlock(nn.Module):
    config: GPTNeoConfig

    @nn.compact
    def __call__(self, x, bias, cache_kv=None, cache_index=None, causal=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        eps = cfg.layer_norm_epsilon
        h = nn.LayerNorm(epsilon=eps, dtype=dtype, name="ln_1")(x)
        attn_out, new_kv = GPTNeoAttention(cfg, name="attn")(
            h, bias, cache_kv, cache_index, causal
        )
        x = x + attn_out
        h = nn.LayerNorm(epsilon=eps, dtype=dtype, name="ln_2")(x)
        x = x + GPTNeoMLP(cfg, name="mlp")(h)
        return x, new_kv


class GPTNeoModel(nn.Module):
    """Same interface as ``GPT2Model`` (incl. hydra hooks)."""

    config: GPTNeoConfig

    def setup(self):
        cfg = self.config
        pdtype = jnp.dtype(cfg.param_dtype)
        self.wte = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, param_dtype=pdtype, name="wte"
        )
        self.wpe = nn.Embed(
            cfg.max_position_embeddings, cfg.hidden_size, param_dtype=pdtype,
            name="wpe",
        )
        self.h = [GPTNeoBlock(cfg, name=f"h_{i}") for i in range(cfg.num_layers)]
        self.ln_f = nn.LayerNorm(
            epsilon=cfg.layer_norm_epsilon, dtype=jnp.dtype(cfg.dtype), name="ln_f"
        )

    def logits(self, hidden: jax.Array) -> jax.Array:
        emb = self.wte.embedding.astype(jnp.dtype(self.config.dtype))
        return jnp.einsum(
            "btd,vd->btv", hidden, emb, preferred_element_type=jnp.float32
        )

    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        position_ids: Optional[jax.Array] = None,
        cache=None,
        cache_index=None,
        start_layer: int = 0,
        hidden_override: Optional[jax.Array] = None,
        capture_hidden_at: Optional[int] = None,
        compute_logits: bool = True,
    ):
        cfg = self.config
        T = input_ids.shape[1] if hidden_override is None else hidden_override.shape[1]

        if hidden_override is not None:
            x = hidden_override.astype(jnp.dtype(cfg.dtype))
        else:
            if position_ids is None:
                if attention_mask is not None and cache is None:
                    position_ids = jnp.clip(
                        jnp.cumsum(attention_mask, axis=-1) - 1, 0, None
                    )
                else:
                    position_ids = jnp.arange(T)[None, :]
            # per-table rounding before the add: keeps the sum invariant to
            # f32-master vs compute-dtype-cast params (rollout weight cast)
            dtype = jnp.dtype(cfg.dtype)
            x = self.wte(input_ids).astype(dtype) + self.wpe(
                position_ids
            ).astype(dtype)

        # global layers share the causal-LM dispatch; local layers always
        # need an explicit band bias (the window isn't expressible as the
        # kernels' causal flag).
        global_bias, causal = causal_dispatch(T, cache, cache_index, attention_mask)
        pad = padding_bias(attention_mask) if attention_mask is not None else None
        if cache is None:
            kv_len, offset = T, 0
        else:
            # mask width == attention view width (the chunked prefill's
            # prompt-only mask narrows the cache view; full-capacity
            # callers are unchanged) — must agree with causal_dispatch
            # or the local band bias misaligns with the padding bias
            kv_len = (
                attention_mask.shape[-1]
                if attention_mask is not None
                else cache[0]["k"].shape[1]
            )
            offset = cache_index
        local_bias = combine_biases(
            local_causal_bias(T, kv_len, cfg.window_size, offset=offset), pad
        )

        types = cfg.layer_types
        new_cache: List = []
        branch_hidden = None
        for i in range(start_layer, cfg.num_layers):
            if capture_hidden_at is not None and i == capture_hidden_at:
                branch_hidden = x
            layer_cache = cache[i] if cache is not None else None
            if types[i] == "local":
                x, new_kv = self.h[i](x, local_bias, layer_cache, cache_index, False)
            else:
                x, new_kv = self.h[i](x, global_bias, layer_cache, cache_index, causal)
            new_cache.append(new_kv)

        x = self.ln_f(x)
        out = {
            "logits": self.logits(x) if compute_logits else None,
            "hidden": x,
            "cache": tuple(new_cache) if cache is not None else None,
        }
        if capture_hidden_at is not None:
            out["branch_hidden"] = branch_hidden
        return out


def init_gpt_neo_cache(config: GPTNeoConfig, batch_size: int, capacity: int):
    from trlx_tpu.models.gpt2 import kv_buffers

    return kv_buffers(
        config.num_layers, batch_size, capacity, config.num_heads,
        config.hidden_size // config.num_heads, config.dtype,
        getattr(config, "kv_cache_dtype", "bfloat16"),
    )
