"""Value / Q heads and the policy wrapper modules.

Re-design of the reference's head machinery:
- ``make_head`` 2-layer MLP (`trlx/model/nn/ppo_models.py:216-222`, bf16 in
  the fork) -> :class:`MLPHead`.
- ``GPTHeadWithValueModel`` (`ppo_models.py:225-289`) ->
  :class:`CausalLMWithValueHead`: backbone + scalar value head, one forward
  returning logits *and* values (no separate ModelOutput class — outputs are
  plain dicts of arrays).
- ``ILQLHeads`` (`trlx/model/nn/ilql_models.py:119-181`) ->
  :class:`ILQLHeads`: V head + twin Q heads. Target-Q params are NOT module
  params here — they live as a separate pytree in the ILQL train state and
  Polyak-sync is a jitted tree op (the ZeRO-3 ``GatheredParameters`` dance at
  `ilql_models.py:170-181` is unnecessary under GSPMD).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from trlx_tpu.models.gpt2 import GPT2Config, GPT2Model


class MLPHead(nn.Module):
    """``make_head`` equivalent: Dense(2n) -> ReLU -> Dense(out)."""

    hidden_size: int
    output_size: int = 1
    dtype: str = "bfloat16"
    param_dtype: str = "float32"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        dtype = jnp.dtype(self.dtype)
        pdtype = jnp.dtype(self.param_dtype)
        x = nn.Dense(self.hidden_size * 2, dtype=dtype, param_dtype=pdtype, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dense(self.output_size, dtype=jnp.float32, param_dtype=pdtype, name="fc2")(x)
        return x


class CausalLMWithValueHead(nn.Module):
    """Causal LM backbone + scalar value head (PPO policy).

    Values are computed in float32 (the head's final layer) — value-loss
    clipping is sensitive to bf16 rounding.
    """

    config: GPT2Config

    def setup(self):
        self.backbone = GPT2Model(self.config, name="transformer")
        self.v_head = MLPHead(
            self.config.n_embd,
            1,
            dtype=self.config.dtype,
            param_dtype=self.config.param_dtype,
            name="v_head",
        )

    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        position_ids: Optional[jax.Array] = None,
        cache=None,
        cache_index=None,
    ):
        out = self.backbone(
            input_ids,
            attention_mask=attention_mask,
            position_ids=position_ids,
            cache=cache,
            cache_index=cache_index,
        )
        out["values"] = self.v_head(out["hidden"])[..., 0]
        return out

    def lm_only(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        position_ids: Optional[jax.Array] = None,
        cache=None,
        cache_index=None,
    ):
        """Backbone forward without the value head (frozen KL reference)."""
        return self.backbone(
            input_ids,
            attention_mask=attention_mask,
            position_ids=position_ids,
            cache=cache,
            cache_index=cache_index,
        )


class ILQLHeads(nn.Module):
    """V head + ``n_qs`` Q heads over full vocab (`ilql_models.py:119-136`).

    Heads map hidden state -> per-token values: Q heads output vocab-size
    action values, V head a scalar state value.
    """

    config: GPT2Config
    two_qs: bool = True

    def setup(self):
        n = self.config.n_embd
        v = self.config.vocab_size
        kw = dict(dtype=self.config.dtype, param_dtype=self.config.param_dtype)
        self.q1_head = MLPHead(n, v, name="q1_head", **kw)
        if self.two_qs:
            self.q2_head = MLPHead(n, v, name="q2_head", **kw)
        self.v_head = MLPHead(n, 1, name="v_head", **kw)

    def __call__(self, hidden: jax.Array) -> Tuple[Tuple[jax.Array, ...], jax.Array]:
        qs = (self.q1_head(hidden),)
        if self.two_qs:
            qs = qs + (self.q2_head(hidden),)
        vs = self.v_head(hidden)[..., 0]
        return qs, vs
