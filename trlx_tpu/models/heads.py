"""Value / Q heads and the policy wrapper modules.

Re-design of the reference's head machinery:
- ``make_head`` 2-layer MLP (`trlx/model/nn/ppo_models.py:216-222`, bf16 in
  the fork) -> :class:`MLPHead`.
- ``GPTHeadWithValueModel`` (`ppo_models.py:225-289`) ->
  :class:`CausalLMWithValueHead`: backbone + scalar value head, one forward
  returning logits *and* values (no separate ModelOutput class — outputs are
  plain dicts of arrays).
- ``ILQLHeads`` (`trlx/model/nn/ilql_models.py:119-181`) ->
  :class:`ILQLHeads`: V head + twin Q heads. Target-Q params are NOT module
  params here — they live as a separate pytree in the ILQL train state and
  Polyak-sync is a jitted tree op (the ZeRO-3 ``GatheredParameters`` dance at
  `ilql_models.py:170-181` is unnecessary under GSPMD).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from trlx_tpu.models.gpt2 import GPT2Config, GPT2Model
from trlx_tpu.models.t5 import T5Config, T5Model


class MLPHead(nn.Module):
    """``make_head`` equivalent: Dense(2n) -> ReLU -> Dense(out)."""

    hidden_size: int
    output_size: int = 1
    dtype: str = "bfloat16"
    param_dtype: str = "float32"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        dtype = jnp.dtype(self.dtype)
        pdtype = jnp.dtype(self.param_dtype)
        x = nn.Dense(self.hidden_size * 2, dtype=dtype, param_dtype=pdtype, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dense(self.output_size, dtype=jnp.float32, param_dtype=pdtype, name="fc2")(x)
        return x


class CausalLMWithValueHead(nn.Module):
    """Causal LM backbone + scalar value head (PPO policy).

    ``backbone_cls`` may be any causal family module with the shared call
    interface (GPT2Model / GPTJModel / NeoXModel). Values are computed in
    float32 (the head's final layer) — value-loss clipping is sensitive to
    bf16 rounding.
    """

    config: Any
    backbone_cls: Any = GPT2Model

    def setup(self):
        from trlx_tpu.models.registry import hidden_size_of

        self.backbone = self.backbone_cls(self.config, name="transformer")
        self.v_head = MLPHead(
            hidden_size_of(self.config),
            1,
            dtype=self.config.dtype,
            param_dtype=self.config.param_dtype,
            name="v_head",
        )

    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        position_ids: Optional[jax.Array] = None,
        cache=None,
        cache_index=None,
        last_only: bool = False,
        skip_heads: bool = False,
    ):
        """``last_only=True`` computes logits/values only for the final
        position (sampler prefill: the [B, Q, vocab] float32 logits tensor
        for the whole prompt would be written to HBM just to read one row).

        ``skip_heads=True`` computes NEITHER head: the caller only wants
        the KV-cache side effect (the chunked prefill's non-final prompt
        chunks — their logits/values are never read, and even the
        ``last_only`` single-row head would pay an LM-head matmul per
        chunk). ``logits``/``values`` are then ``None``.
        """
        out = self.backbone(
            input_ids,
            attention_mask=attention_mask,
            position_ids=position_ids,
            cache=cache,
            cache_index=cache_index,
            compute_logits=not (last_only or skip_heads),
        )
        if skip_heads:
            out["values"] = None
        elif last_only:
            h = out["hidden"][:, -1:]
            out["logits"] = self.backbone.logits(h)
            out["values"] = self.v_head(h)[..., 0]
        else:
            out["values"] = self.v_head(out["hidden"])[..., 0]
        return out

    def response_forward(
        self,
        input_ids: jax.Array,
        attention_mask: jax.Array,
        query_length: int,
    ):
        """(logits, values) over response-predicting positions only.

        The PPO update needs logits/values at positions Q-1..Q+R-2 (the
        states that predict each response token); computing the LM head for
        the query positions too would write (and backprop through) a
        [B, Q+R, vocab] float32 tensor for nothing.
        """
        h, values = self.response_hidden(
            input_ids, attention_mask, query_length
        )
        return self.backbone.logits(h), values

    def response_hidden(
        self,
        input_ids: jax.Array,
        attention_mask: jax.Array,
        query_length: int,
    ):
        """(hidden, values) over response-predicting positions — the
        logits-free half of :meth:`response_forward`, for callers that
        compute logprobs chunked (``train.logprob_chunk``) instead of
        materializing the [B, R, vocab] f32 logits buffer."""
        out = self.backbone(
            input_ids, attention_mask=attention_mask, compute_logits=False
        )
        h = out["hidden"][:, query_length - 1 : -1]
        return h, self.v_head(h)[..., 0]

    def lm_only(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        position_ids: Optional[jax.Array] = None,
        cache=None,
        cache_index=None,
    ):
        """Backbone forward without the value head (frozen KL reference)."""
        return self.backbone(
            input_ids,
            attention_mask=attention_mask,
            position_ids=position_ids,
            cache=cache,
            cache_index=cache_index,
        )


class T5WithValueHead(nn.Module):
    """T5/UL2 + scalar value head on decoder hidden states — the fork's
    policy model (``T5HeadWithValueModel``, `ppo_models.py:607-655`; value
    head on ``d_model``, applied to decoder hidden states :638-641, but
    without the reference's fragile ``decoder_hidden_states`` tuple-vs-tensor
    assumption).

    Methods mirror the backbone's: full teacher-forced ``__call__`` plus
    ``encode`` / ``decode`` / ``init_cross_kv`` for compiled sampling.
    """

    config: T5Config

    def setup(self):
        self.backbone = T5Model(self.config, name="t5")
        self.v_head = MLPHead(
            self.config.d_model,
            1,
            dtype=self.config.dtype,
            param_dtype=self.config.param_dtype,
            name="v_head",
        )

    def __call__(
        self,
        input_ids,
        attention_mask=None,
        decoder_input_ids=None,
        decoder_attention_mask=None,
    ):
        out = self.backbone(
            input_ids,
            attention_mask=attention_mask,
            decoder_input_ids=decoder_input_ids,
            decoder_attention_mask=decoder_attention_mask,
        )
        out["values"] = self.v_head(out["hidden"])[..., 0]
        return out

    def encode(self, input_ids, attention_mask=None):
        return self.backbone.encode(input_ids, attention_mask)

    def init_cross_kv(self, encoder_hidden):
        return self.backbone.init_cross_kv(encoder_hidden)

    def decode(
        self,
        decoder_input_ids,
        encoder_mask=None,
        decoder_mask=None,
        cache=None,
        cache_index=None,
        cross_kv=None,
    ):
        out = self.backbone.decode(
            decoder_input_ids,
            encoder_mask=encoder_mask,
            decoder_mask=decoder_mask,
            cache=cache,
            cache_index=cache_index,
            cross_kv=cross_kv,
        )
        out["values"] = self.v_head(out["hidden"])[..., 0]
        return out


class ILQLHeads(nn.Module):
    """V head + ``n_qs`` Q heads over full vocab (`ilql_models.py:119-136`).

    Q heads map action-state hidden -> vocab-size action values; the V head
    maps state hidden -> a scalar. Target-Q evaluation reuses the same
    module applied with a *separate target param tree* (see
    ``CausalLMWithILQLHeads.target_qs``), replacing the reference's frozen
    ``target_q_heads`` submodules + ZeRO-gather sync (`ilql_models.py:170-181`).
    """

    config: Any
    two_qs: bool = True

    def setup(self):
        from trlx_tpu.models.registry import hidden_size_of

        n = hidden_size_of(self.config)
        v = self.config.vocab_size
        kw = dict(dtype=self.config.dtype, param_dtype=self.config.param_dtype)
        self.q_heads = [
            MLPHead(n, v, name=f"q{i+1}_head", **kw)
            for i in range(2 if self.two_qs else 1)
        ]
        self.v_head = MLPHead(n, 1, name="v_head", **kw)

    def q(self, action_hidden: jax.Array) -> Tuple[jax.Array, ...]:
        return tuple(h(action_hidden) for h in self.q_heads)

    def v(self, state_hidden: jax.Array) -> jax.Array:
        return self.v_head(state_hidden)[..., 0]

    def __call__(self, action_hidden, state_hidden):
        return self.q(action_hidden), self.v(state_hidden)


class CausalLMWithILQLHeads(nn.Module):
    """Causal LM + ILQL heads (reference ``CausalLMWithValueHeads``,
    `ilql_models.py:184-335`).

    Forward gathers hidden states at ``states_ixs``/``actions_ixs``
    (`ilql_models.py:138-159`) and returns ``(logits, qs, vs,
    action_hidden)``; target-Q values come from :meth:`target_qs` applied
    with the target param tree held in the ILQL train state.
    """

    config: Any
    two_qs: bool = True
    backbone_cls: Any = GPT2Model

    def setup(self):
        self.backbone = self.backbone_cls(self.config, name="transformer")
        self.ilql_heads = ILQLHeads(self.config, self.two_qs, name="heads")

    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        position_ids: Optional[jax.Array] = None,
        actions_ixs: Optional[jax.Array] = None,
        states_ixs: Optional[jax.Array] = None,
        cache=None,
        cache_index=None,
        last_only: bool = False,
    ):
        """``last_only=True``: logits and Q/V heads only for the final
        position (sampler prefill — the advantage-shifted decode reads one
        row; without this the prefill writes [B, Q, vocab] logits plus
        per-position Q/V for the whole prompt)."""
        out = self.backbone(
            input_ids,
            attention_mask=attention_mask,
            position_ids=position_ids,
            cache=cache,
            cache_index=cache_index,
            compute_logits=not last_only,
        )
        hidden = out["hidden"]
        if last_only:
            if actions_ixs is not None or states_ixs is not None:
                raise ValueError(
                    "last_only truncates hidden to the final position; "
                    "actions_ixs/states_ixs gathers would silently clamp "
                    "to it — these options are mutually exclusive"
                )
            hidden = hidden[:, -1:]
            out["logits"] = self.backbone.logits(hidden)
        if actions_ixs is not None:
            action_hidden = jnp.take_along_axis(
                hidden, actions_ixs[..., None], axis=1
            )
        else:
            action_hidden = hidden
        if states_ixs is not None:
            state_hidden = jnp.take_along_axis(hidden, states_ixs[..., None], axis=1)
        else:
            state_hidden = hidden
        qs, vs = self.ilql_heads(action_hidden, state_hidden)
        out.update(qs=qs, vs=vs, action_hidden=action_hidden)
        return out

    def target_qs(self, action_hidden: jax.Array) -> Tuple[jax.Array, ...]:
        """Q heads only — apply with ``{"params": {"heads": target_tree}}``."""
        return self.ilql_heads.q(action_hidden)
