"""GPT-NeoX causal LM (the reference README's 20B stretch target).

Architecture vs GPT-J: fused QKV projection (HF's head-major ``[H, 3*Dh]``
layout preserved so conversion is a transpose-only copy), partial rotary
(``rotary_pct`` of each head dim, half-rotation convention), parallel
residual with *separate* layernorms for attention and MLP
(``use_parallel_residual``), untied ``embed_out`` head without bias.
Same call interface as ``GPT2Model``/``GPTJModel``.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from trlx_tpu.ops.attention import causal_dispatch, dot_product_attention
from trlx_tpu.ops.rotary import apply_rotary_half, rotary_angles


@dataclass(frozen=True)
class NeoXConfig:
    vocab_size: int = 50432
    max_position_embeddings: int = 2048
    hidden_size: int = 6144
    num_hidden_layers: int = 44
    num_attention_heads: int = 64
    rotary_pct: float = 0.25
    rotary_emb_base: float = 10000.0
    use_parallel_residual: bool = True
    layer_norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    param_dtype: str = "float32"
    # rollout KV-cache storage ("bfloat16" | "int8" | "auto"); see
    # models/gpt2.py::write_cache — decode is HBM-bound and the
    # cache is its dominant traffic, int8 halves it
    kv_cache_dtype: str = "bfloat16"

    def __post_init__(self):
        from trlx_tpu.models.gpt2 import validate_kv_cache_dtype

        validate_kv_cache_dtype(self.kv_cache_dtype)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "NeoXConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    @property
    def n_layer(self) -> int:
        return self.num_hidden_layers

    @property
    def n_embd(self) -> int:
        return self.hidden_size


NEOX_PARTITION_RULES = [
    (r"wte/embedding", P(None, "tp")),
    (r"attn/query_key_value/kernel", P(None, "tp")),
    (r"attn/dense/kernel", P("tp", None)),
    (r"mlp/dense_h_to_4h/kernel", P(None, "tp")),
    (r"mlp/dense_4h_to_h/kernel", P("tp", None)),
    (r"lm_head/kernel", P(None, "tp")),
]


class NeoXAttention(nn.Module):
    config: NeoXConfig

    @nn.compact
    def __call__(self, x, bias, position_ids, cache_kv=None, cache_index=None, causal=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        pdtype = jnp.dtype(cfg.param_dtype)
        B, T, D = x.shape
        H = cfg.num_attention_heads
        head_dim = cfg.hidden_size // H
        rotary_dim = int(head_dim * cfg.rotary_pct)

        qkv = nn.Dense(
            3 * cfg.hidden_size, dtype=dtype, param_dtype=pdtype,
            name="query_key_value",
        )(x)
        # HF layout: [B, T, H, 3*Dh] -> q/k/v slices per head
        qkv = qkv.reshape(B, T, H, 3 * head_dim)
        q = qkv[..., :head_dim]
        k = qkv[..., head_dim : 2 * head_dim]
        v = qkv[..., 2 * head_dim :]

        sin, cos = rotary_angles(position_ids, rotary_dim, cfg.rotary_emb_base)
        q = apply_rotary_half(q, sin, cos, rotary_dim)
        k = apply_rotary_half(k, sin, cos, rotary_dim)

        new_kv = None
        if cache_kv is not None:
            from trlx_tpu.models.gpt2 import write_cache

            # bias width == attention view width (a prompt-only mask —
            # the chunked prefill — narrows the cache view to match)
            view_len = bias.shape[-1] if bias is not None else None
            k, v, new_kv = write_cache(
                cache_kv, k, v, cache_index, dtype, view_len=view_len
            )

        out = dot_product_attention(q, k, v, bias, causal=causal)
        out = out.reshape(B, T, cfg.hidden_size)
        out = nn.Dense(
            cfg.hidden_size, dtype=dtype, param_dtype=pdtype, name="dense"
        )(out)
        return out, new_kv


class NeoXMLP(nn.Module):
    config: NeoXConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        pdtype = jnp.dtype(cfg.param_dtype)
        h = nn.Dense(
            4 * cfg.hidden_size, dtype=dtype, param_dtype=pdtype,
            name="dense_h_to_4h",
        )(x)
        h = nn.gelu(h, approximate=True)
        return nn.Dense(
            cfg.hidden_size, dtype=dtype, param_dtype=pdtype, name="dense_4h_to_h"
        )(h)


class NeoXBlock(nn.Module):
    config: NeoXConfig

    @nn.compact
    def __call__(self, x, bias, position_ids, cache_kv=None, cache_index=None, causal=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        ln_attn = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dtype, name="ln_1")(x)
        attn_out, new_kv = NeoXAttention(cfg, name="attn")(
            ln_attn, bias, position_ids, cache_kv, cache_index, causal
        )
        if cfg.use_parallel_residual:
            ln_mlp = nn.LayerNorm(
                epsilon=cfg.layer_norm_eps, dtype=dtype, name="ln_2"
            )(x)
            return x + attn_out + NeoXMLP(cfg, name="mlp")(ln_mlp), new_kv
        x = x + attn_out
        ln_mlp = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dtype, name="ln_2")(x)
        return x + NeoXMLP(cfg, name="mlp")(ln_mlp), new_kv


class NeoXModel(nn.Module):
    """Same interface as ``GPT2Model`` (incl. hydra hooks)."""

    config: NeoXConfig

    def setup(self):
        cfg = self.config
        pdtype = jnp.dtype(cfg.param_dtype)
        self.wte = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, param_dtype=pdtype, name="wte"
        )
        self.h = [NeoXBlock(cfg, name=f"h_{i}") for i in range(cfg.num_hidden_layers)]
        self.ln_f = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=jnp.dtype(cfg.dtype), name="ln_f"
        )
        self.lm_head = nn.Dense(
            cfg.vocab_size,
            use_bias=False,
            dtype=jnp.dtype(cfg.dtype),
            param_dtype=pdtype,
            name="lm_head",
        )

    def logits(self, hidden: jax.Array) -> jax.Array:
        """LM head on (already ln_f-normalized) hidden states; float32."""
        return self.lm_head(hidden).astype(jnp.float32)

    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        position_ids: Optional[jax.Array] = None,
        cache=None,
        cache_index=None,
        start_layer: int = 0,
        hidden_override: Optional[jax.Array] = None,
        capture_hidden_at: Optional[int] = None,
        compute_logits: bool = True,
    ):
        cfg = self.config
        T = input_ids.shape[1] if hidden_override is None else hidden_override.shape[1]

        if position_ids is None:
            if attention_mask is not None and cache is None:
                position_ids = jnp.clip(jnp.cumsum(attention_mask, axis=-1) - 1, 0, None)
            else:
                position_ids = jnp.broadcast_to(
                    jnp.arange(T)[None, :], (input_ids.shape[0], T)
                )
        else:
            position_ids = jnp.broadcast_to(position_ids, (input_ids.shape[0], T))

        if hidden_override is not None:
            x = hidden_override.astype(jnp.dtype(cfg.dtype))
        else:
            x = self.wte(input_ids).astype(jnp.dtype(cfg.dtype))

        bias, causal = causal_dispatch(T, cache, cache_index, attention_mask)

        new_cache: List = []
        branch_hidden = None
        for i in range(start_layer, cfg.num_hidden_layers):
            if capture_hidden_at is not None and i == capture_hidden_at:
                branch_hidden = x
            layer_cache = cache[i] if cache is not None else None
            x, new_kv = self.h[i](x, bias, position_ids, layer_cache, cache_index, causal)
            new_cache.append(new_kv)

        x = self.ln_f(x)
        out = {
            "logits": self.logits(x) if compute_logits else None,
            "hidden": x,
            "cache": tuple(new_cache) if cache is not None else None,
        }
        if capture_hidden_at is not None:
            out["branch_hidden"] = branch_hidden
        return out


def init_neox_cache(config: NeoXConfig, batch_size: int, capacity: int):
    from trlx_tpu.models.gpt2 import kv_buffers

    return kv_buffers(
        config.num_hidden_layers, batch_size, capacity,
        config.num_attention_heads,
        config.hidden_size // config.num_attention_heads, config.dtype,
        getattr(config, "kv_cache_dtype", "bfloat16"),
    )
