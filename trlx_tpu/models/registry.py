"""Model-family registry: ``model.model_type`` string -> architecture kit.

The reference hardwires architectures per trainer (`accelerate_ppo_model.py
:56-59` -> T5; `ilql_models.py:187` -> AutoModelForCausalLM). Here every
causal family (gpt2, gptj, gpt_neox) exposes one uniform kit — config class,
backbone module (same call interface), TP partition rules, KV-cache factory,
checkpoint loader — so trainers are family-agnostic; seq2seq (t5) has its
own trainer subclass.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence, Tuple


@dataclass(frozen=True)
class ModelFamily:
    name: str
    config_cls: type
    backbone_cls: type
    partition_rules: Sequence
    init_cache: Callable  # (config, batch, capacity) -> cache
    load_checkpoint: Callable  # (path, dtype) -> (config, params)
    is_seq2seq: bool = False
    # has switch-MoE experts an `ep` mesh axis can shard (models/gpt2_moe.py)
    supports_ep: bool = False


_FAMILIES: Dict[str, ModelFamily] = {}


def register_model_family(family: ModelFamily, *aliases: str) -> ModelFamily:
    for key in (family.name, *aliases):
        _FAMILIES[key.lower()] = family
    return family


def get_model_family(name: str) -> ModelFamily:
    key = name.lower()
    if key not in _FAMILIES:
        _register_builtins()
    if key in _FAMILIES:
        return _FAMILIES[key]
    raise ValueError(
        f"Unknown model_type: {name!r}. Registered: {sorted(_FAMILIES)}"
    )


def hidden_size_of(config: Any) -> int:
    for attr in ("n_embd", "hidden_size", "d_model"):
        if hasattr(config, attr):
            return getattr(config, attr)
    raise ValueError(f"no hidden size on {type(config).__name__}")


def n_heads_of(config: Any) -> int:
    for attr in ("n_head", "num_heads", "num_attention_heads"):
        if hasattr(config, attr):
            return getattr(config, attr)
    raise ValueError(f"no head count on {type(config).__name__}")


def num_layers_of(config: Any) -> int:
    # order matters: T5 has both num_layers (encoder) and num_decoder_layers —
    # trainers freeze/branch on the decoder stack, so it takes precedence
    for attr in ("n_layer", "num_hidden_layers", "num_decoder_layers", "num_layers"):
        if hasattr(config, attr):
            return getattr(config, attr)
    raise ValueError(f"no layer count on {type(config).__name__}")


def _register_builtins() -> None:
    from trlx_tpu.models import conversion
    from trlx_tpu.models.gpt2 import GPT2Config, GPT2Model, PARTITION_RULES, init_cache
    from trlx_tpu.models.gptj import (
        GPTJConfig,
        GPTJModel,
        GPTJ_PARTITION_RULES,
        init_gptj_cache,
    )
    from trlx_tpu.models.gpt_neo import (
        GPTNeoConfig,
        GPTNeoModel,
        GPT_NEO_PARTITION_RULES,
        init_gpt_neo_cache,
    )
    from trlx_tpu.models.neox import (
        NeoXConfig,
        NeoXModel,
        NEOX_PARTITION_RULES,
        init_neox_cache,
    )
    from trlx_tpu.models.t5 import T5Config, T5Model, T5_PARTITION_RULES, init_t5_cache

    register_model_family(
        ModelFamily(
            "gpt2", GPT2Config, GPT2Model, PARTITION_RULES, init_cache,
            conversion.load_gpt2_checkpoint,
        )
    )
    register_model_family(
        ModelFamily(
            "gptj", GPTJConfig, GPTJModel, GPTJ_PARTITION_RULES, init_gptj_cache,
            conversion.load_gptj_checkpoint,
        ),
        "gpt-j",
    )
    register_model_family(
        ModelFamily(
            "gpt_neo", GPTNeoConfig, GPTNeoModel, GPT_NEO_PARTITION_RULES,
            init_gpt_neo_cache, conversion.load_gpt_neo_checkpoint,
        ),
        "gpt-neo",
    )
    register_model_family(
        ModelFamily(
            "gpt_neox", NeoXConfig, NeoXModel, NEOX_PARTITION_RULES, init_neox_cache,
            conversion.load_neox_checkpoint,
        ),
        "neox",
        "gpt-neox",
    )
    register_model_family(
        ModelFamily(
            "t5", T5Config, T5Model, T5_PARTITION_RULES, init_t5_cache,
            conversion.load_t5_checkpoint, is_seq2seq=True,
        ),
        "ul2",
    )
    from trlx_tpu.models.gpt2_moe import (
        GPT2MoEConfig,
        GPT2MoEModel,
        GPT2_MOE_PARTITION_RULES,
        _no_checkpoint,
    )

    register_model_family(
        ModelFamily(
            "gpt2_moe", GPT2MoEConfig, GPT2MoEModel, GPT2_MOE_PARTITION_RULES,
            init_cache, _no_checkpoint, supports_ep=True,
        ),
        "gpt2-moe",
    )
