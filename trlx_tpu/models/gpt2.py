"""GPT-2-family causal LM, written TPU-first in flax.linen.

Native re-implementation of the architecture behind the reference's
``GPTHeadWithValueModel`` / ``GPTHydraHeadWithValueModel``
(``trlx/model/nn/ppo_models.py:225-603``), which wrap HF torch GPT-2. Here
the transformer itself is a JAX module so that:

- generation runs as one compiled program (prefill + ``lax.scan`` decode over
  an explicit KV-cache pytree) instead of HF's Python token loop;
- hidden-dim / head-dim matmuls carry tensor-parallel sharding rules
  (``partition_rules``) for the mesh's ``tp`` axis;
- the hydra frozen-branch trick (`ppo_models.py:505-558`) is a plain
  ``blocks_from`` method re-running the top-k blocks with frozen params.

Weight-compatible with HF GPT-2 checkpoints via
``trlx_tpu.models.conversion`` (HF Conv1D stores kernels as (in, out), which
matches flax Dense — conversion is a transpose-free copy).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from trlx_tpu.ops.attention import causal_dispatch, dot_product_attention

# KV cache: tuple over layers of {"k": [B, C, H, Dh], "v": [B, C, H, Dh]}
Cache = Tuple[Dict[str, jax.Array], ...]


VALID_KV_CACHE_DTYPES = ("bfloat16", "int8", "auto")


def validate_kv_cache_dtype(value: str) -> None:
    """Shared __post_init__ validation for every causal family config."""
    if value not in VALID_KV_CACHE_DTYPES:
        raise ValueError(
            f"kv_cache_dtype={value!r} is not supported (choose one of "
            f"{VALID_KV_CACHE_DTYPES}) — an unrecognized value would "
            "otherwise silently fall back to bf16 buffers"
        )


@dataclass(frozen=True)
class GPT2Config:
    """Architecture hyperparameters (HF ``GPT2Config`` field names)."""

    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    layer_norm_epsilon: float = 1e-5
    dtype: str = "bfloat16"  # compute dtype (MXU path)
    param_dtype: str = "float32"
    # Rollout KV-cache storage. Single-token decode is HBM-bound and the
    # cache is its dominant traffic (grows with context while weights
    # stay fixed), so "int8" halves the bottleneck: K/V quantized per
    # (token, head) on write (absmax/127 scale), dequantized on read
    # inside the attention matmul's operand fusion. Training/scoring
    # forwards never touch this — only the sampler's cache buffers.
    # "auto" resolves per cache shape: int8 below the measured capacity
    # crossover (INT8_KV_MAX_CAPACITY), bf16 beyond it.
    kv_cache_dtype: str = "bfloat16"  # "bfloat16" | "int8" | "auto"

    def __post_init__(self):
        validate_kv_cache_dtype(self.kv_cache_dtype)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "GPT2Config":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


# Tensor-parallel placement: attention/MLP input projections shard the output
# dim, output projections shard the input dim, so each block needs a single
# all-reduce of activations (inserted by GSPMD) per sub-layer.
PARTITION_RULES = [
    (r"wte/embedding", P(None, "tp")),
    (r"attn/c_attn/kernel", P(None, "tp")),
    (r"attn/c_proj/kernel", P("tp", None)),
    (r"mlp/c_fc/kernel", P(None, "tp")),
    (r"mlp/c_proj/kernel", P("tp", None)),
]


class MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        x = nn.Dense(4 * cfg.n_embd, dtype=dtype, param_dtype=jnp.dtype(cfg.param_dtype), name="c_fc")(x)
        x = nn.gelu(x, approximate=True)  # GPT-2 uses gelu_new
        x = nn.Dense(cfg.n_embd, dtype=dtype, param_dtype=jnp.dtype(cfg.param_dtype), name="c_proj")(x)
        return x


class Attention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(
        self,
        x: jax.Array,  # [B, T, D]
        bias: Optional[jax.Array],
        cache_kv: Optional[Dict[str, jax.Array]] = None,
        cache_index: Optional[jax.Array] = None,
        causal: bool = False,
    ) -> Tuple[jax.Array, Optional[Dict[str, jax.Array]]]:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        pdtype = jnp.dtype(cfg.param_dtype)
        B, T, D = x.shape
        head_dim = cfg.n_embd // cfg.n_head

        qkv = nn.Dense(3 * cfg.n_embd, dtype=dtype, param_dtype=pdtype, name="c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, cfg.n_head, head_dim)
        k = k.reshape(B, T, cfg.n_head, head_dim)
        v = v.reshape(B, T, cfg.n_head, head_dim)

        new_kv = None
        if cache_kv is not None:
            # Write this step's keys/values into the capacity buffer at
            # cache_index, then attend over the buffer VIEW the bias was
            # built for (invalid positions are masked by `bias`; a bias
            # narrower than capacity — the chunked prefill's prompt-only
            # mask — narrows the attention view to match).
            view_len = bias.shape[-1] if bias is not None else None
            k, v, new_kv = write_cache(
                cache_kv, k, v, cache_index, dtype, view_len=view_len
            )

        out = dot_product_attention(q, k, v, bias, causal=causal)
        out = out.reshape(B, T, cfg.n_embd)
        out = nn.Dense(cfg.n_embd, dtype=dtype, param_dtype=pdtype, name="c_proj")(out)
        return out, new_kv


class Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, bias, cache_kv=None, cache_index=None, causal=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        eps = cfg.layer_norm_epsilon
        h = nn.LayerNorm(epsilon=eps, dtype=dtype, name="ln_1")(x)
        attn_out, new_kv = Attention(cfg, name="attn")(
            h, bias, cache_kv, cache_index, causal
        )
        x = x + attn_out
        h = nn.LayerNorm(epsilon=eps, dtype=dtype, name="ln_2")(x)
        x = x + MLP(cfg, name="mlp")(h)
        return x, new_kv


class GPT2Model(nn.Module):
    """GPT-2 transformer with tied-embedding LM head and explicit KV cache.

    Call modes (all jit-safe, static shapes):
    - training/scoring: ``cache=None`` — full-sequence causal forward.
    - prefill/decode:   ``cache`` given — keys/values written at
      ``cache_index`` into fixed-capacity buffers; ``bias`` must mask
      invalid cache positions (built by the sampler).
    """

    config: GPT2Config

    def setup(self):
        cfg = self.config
        pdtype = jnp.dtype(cfg.param_dtype)
        self.wte = nn.Embed(cfg.vocab_size, cfg.n_embd, param_dtype=pdtype, name="wte")
        self.wpe = nn.Embed(cfg.n_positions, cfg.n_embd, param_dtype=pdtype, name="wpe")
        self.h = [Block(cfg, name=f"h_{i}") for i in range(cfg.n_layer)]
        self.ln_f = nn.LayerNorm(
            epsilon=cfg.layer_norm_epsilon, dtype=jnp.dtype(cfg.dtype), name="ln_f"
        )

    def embed(self, input_ids: jax.Array, position_ids: jax.Array) -> jax.Array:
        # each table rounds to the compute dtype BEFORE the add, so the sum
        # is invariant to whether params are stored f32 or pre-cast to the
        # compute dtype (the rollout-phase weight cast relies on this)
        dtype = jnp.dtype(self.config.dtype)
        return self.wte(input_ids).astype(dtype) + self.wpe(position_ids).astype(dtype)

    def logits(self, hidden: jax.Array) -> jax.Array:
        """Tied LM head; logits in float32 for stable softmax/log-softmax."""
        emb = self.wte.embedding.astype(jnp.dtype(self.config.dtype))
        return jnp.einsum(
            "btd,vd->btv", hidden, emb, preferred_element_type=jnp.float32
        )

    def __call__(
        self,
        input_ids: jax.Array,  # [B, T]
        attention_mask: Optional[jax.Array] = None,  # [B, T] (no cache) / [B, C] (cache)
        position_ids: Optional[jax.Array] = None,
        cache: Optional[Cache] = None,
        cache_index: Optional[jax.Array] = None,
        start_layer: int = 0,
        hidden_override: Optional[jax.Array] = None,
        capture_hidden_at: Optional[int] = None,
        compute_logits: bool = True,
    ):
        """Returns ``{"logits", "hidden", "cache"[, "branch_hidden"]}``.

        ``compute_logits=False`` skips the LM head (callers that only need a
        slice of positions apply :meth:`logits` to sliced hidden — the full
        [B, T, vocab] float32 tensor is the single most expensive
        intermediate in the PPO update).

        The hydra frozen-branch mechanism (`ppo_models.py:505-558`):
        ``capture_hidden_at=k`` additionally returns the activation entering
        block k; ``start_layer=k`` + ``hidden_override`` re-runs blocks
        ``k..n_layer`` from that activation (with the frozen branch's own
        params) to produce reference logits without a second trunk pass.
        """
        cfg = self.config
        T = input_ids.shape[1] if hidden_override is None else hidden_override.shape[1]

        if hidden_override is not None:
            x = hidden_override.astype(jnp.dtype(cfg.dtype))
        else:
            if position_ids is None:
                if attention_mask is not None and cache is None:
                    position_ids = jnp.clip(
                        jnp.cumsum(attention_mask, axis=-1) - 1, 0, None
                    )
                else:
                    position_ids = jnp.arange(T)[None, :]
            x = self.embed(input_ids, position_ids)

        bias, causal = causal_dispatch(T, cache, cache_index, attention_mask)

        new_cache: List = []
        branch_hidden = None
        for i in range(start_layer, cfg.n_layer):
            if capture_hidden_at is not None and i == capture_hidden_at:
                branch_hidden = x
            layer_cache = cache[i] if cache is not None else None
            x, new_kv = self.h[i](x, bias, layer_cache, cache_index, causal)
            new_cache.append(new_kv)

        x = self.ln_f(x)
        out = {
            "logits": self.logits(x) if compute_logits else None,
            "hidden": x,
            "cache": tuple(new_cache) if cache is not None else None,
        }
        if capture_hidden_at is not None:
            out["branch_hidden"] = branch_hidden
        return out


def quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization over the head dim: per (batch, token,
    head) absmax/127 scale. Returns (int8 values, scale[..., :1])."""
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(scale, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.bfloat16)


def write_cache(cache_kv, k, v, cache_index, dtype, view_len=None):
    """Write this step's K/V into the capacity buffers at ``cache_index``;
    returns ``(k, v, new_kv)`` — the full buffers to attend over and the
    updated cache dict. Transparent over the three storage layouts
    (shared by every causal family):

    - plain: ``{"k", "v"}`` in the compute dtype;
    - int8 (``kv_cache_dtype="int8"``): quantize the new slice, store
      value+scale, dequantize the whole buffer for attention — the
      convert+mul folds into the attention matmuls' operand read, so HBM
      sees int8, the MXU sees bf16;
    - paged (``"block_tables"`` present — the continuous-batching
      engine's cache, ``inference/kv_cache.py``): writes resolve logical
      positions through per-slot block tables (``cache_index`` may be a
      per-slot [B] vector), reads return the logical view; composes
      with the int8 layout.

    ``view_len`` (static) narrows the RETURNED attention view to the
    leading ``view_len`` logical positions — the families derive it from
    their attention bias width (``ops/attention.py::causal_dispatch``:
    mask width == view width), so the chunked prefill's prompt-chunk
    forwards never read (or pay attention FLOPs over) the decode region.
    ``None``/full-capacity is byte-identical to the unnarrowed program;
    writes always resolve at full capacity.
    """
    if "block_tables" in cache_kv:
        from trlx_tpu.inference.kv_cache import paged_write_read

        return paged_write_read(
            cache_kv, k, v, cache_index, dtype, view_len=view_len or 0
        )
    at = (0, cache_index, 0, 0)
    capacity = cache_kv["k"].shape[1]
    narrow = view_len is not None and 0 < view_len < capacity
    if "k_scale" in cache_kv:
        k_q, k_s = quantize_kv(k)
        v_q, v_s = quantize_kv(v)
        new_kv = {
            "k": jax.lax.dynamic_update_slice(cache_kv["k"], k_q, at),
            "v": jax.lax.dynamic_update_slice(cache_kv["v"], v_q, at),
            "k_scale": jax.lax.dynamic_update_slice(
                cache_kv["k_scale"], k_s, at
            ),
            "v_scale": jax.lax.dynamic_update_slice(
                cache_kv["v_scale"], v_s, at
            ),
        }
        k_read = new_kv["k"][:, :view_len] if narrow else new_kv["k"]
        v_read = new_kv["v"][:, :view_len] if narrow else new_kv["v"]
        k_s_read = new_kv["k_scale"][:, :view_len] if narrow else new_kv["k_scale"]
        v_s_read = new_kv["v_scale"][:, :view_len] if narrow else new_kv["v_scale"]
        k = k_read.astype(dtype) * k_s_read.astype(dtype)
        v = v_read.astype(dtype) * v_s_read.astype(dtype)
        return k, v, new_kv
    k = jax.lax.dynamic_update_slice(cache_kv["k"], k, at)
    v = jax.lax.dynamic_update_slice(cache_kv["v"], v, at)
    new_kv = {"k": k, "v": v}
    if narrow:
        return k[:, :view_len], v[:, :view_len], new_kv
    return k, v, new_kv


# Measured crossover for the int8 KV cache (LONGCTX.json): int8 wins 1.10x
# at capacity 112 (the B=128 rollout shape — cache traffic dominates and
# the dequant folds into the attention matmul read) but loses ~2x at a 2k
# cache (B=8 long-context decode — XLA materializes the dequantized bf16
# buffer instead of fusing the int8*scale read). The threshold sits
# conservatively between the two measured points; a dequant-fused Pallas
# decode read is the known fix if long-context rollouts ever dominate.
INT8_KV_MAX_CAPACITY = 512


def resolve_kv_cache_dtype(kv_cache_dtype: str, capacity: int) -> str:
    """Resolve ``"auto"`` by cache capacity and warn when an explicit
    ``"int8"`` is forced past the measured crossover — a long-context
    config must not silently decode 2x slower (VERDICT r3 #6)."""
    if kv_cache_dtype == "auto":
        return "int8" if capacity <= INT8_KV_MAX_CAPACITY else "bfloat16"
    if kv_cache_dtype == "int8" and capacity > INT8_KV_MAX_CAPACITY:
        import warnings

        warnings.warn(
            f"kv_cache_dtype='int8' with a {capacity}-token cache: measured "
            f"~2x SLOWER than bfloat16 beyond ~{INT8_KV_MAX_CAPACITY} "
            "(LONGCTX.json decode, B=8/2k — XLA materializes the "
            "dequantized buffer); set kv_cache_dtype='auto' to pick the "
            "faster layout per shape, or 'bfloat16' to silence this"
        )
    return kv_cache_dtype


def kv_buffers(
    n_layer: int,
    batch_size: int,
    capacity: int,
    n_head: int,
    head_dim: int,
    dtype,
    kv_cache_dtype: str = "bfloat16",
) -> Cache:
    """Per-layer fixed-capacity KV buffers, shared by every causal family.
    ``"int8"`` stores int8 values + per (token, head) bf16 scales — ~half
    the HBM traffic of a bf16 cache (`write_cache` handles both);
    ``"auto"`` picks int8 only below the measured capacity crossover."""
    shape = (batch_size, capacity, n_head, head_dim)
    kv_cache_dtype = resolve_kv_cache_dtype(kv_cache_dtype, capacity)
    if kv_cache_dtype == "int8":
        sshape = (batch_size, capacity, n_head, 1)
        return tuple(
            {
                "k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(sshape, jnp.bfloat16),
                "v_scale": jnp.zeros(sshape, jnp.bfloat16),
            }
            for _ in range(n_layer)
        )
    if kv_cache_dtype != "bfloat16":
        raise ValueError(
            f"kv_cache_dtype={kv_cache_dtype!r} is not supported (choose "
            "'bfloat16' or 'int8') — an unrecognized value would otherwise "
            "silently fall back to bf16 buffers"
        )
    return tuple(
        {"k": jnp.zeros(shape, jnp.dtype(dtype)),
         "v": jnp.zeros(shape, jnp.dtype(dtype))}
        for _ in range(n_layer)
    )


def init_cache(config: GPT2Config, batch_size: int, capacity: int) -> Cache:
    """Fixed-capacity KV buffers (one compile for the whole decode loop)."""
    return kv_buffers(
        config.n_layer, batch_size, capacity, config.n_head,
        config.n_embd // config.n_head, config.dtype,
        getattr(config, "kv_cache_dtype", "bfloat16"),
    )
