"""T5/UL2 encoder-decoder, written TPU-first in flax.linen.

Native re-implementation of the architecture behind the fork's
``T5HeadWithValueModel`` (``trlx/model/nn/ppo_models.py:607-655``, which
wraps HF ``AutoModelForSeq2SeqLM`` in bf16). Differences from the GPT-2
stack that this file owns:

- RMS layer norm without bias/mean-centering (fp32), pre-norm residuals;
- relative position bias buckets (encoder bidirectional, decoder causal),
  parameterized only in layer 0 and shared down the stack;
- unscaled attention (T5 folds the 1/sqrt(d) into initialization);
- ReLU or gated-GELU feed-forward (UL2/v1.1 uses gated);
- tied or untied LM head (v1.1/UL2 untie; tied head rescales by
  ``d_model**-0.5``);
- decoder self-attention KV cache + precomputed cross-attention KV for the
  compiled seq2seq sampler (``ops/sampling.py::make_seq2seq_sampler``).

Weight-compatible with HF T5/MT5/UL2 checkpoints via
``trlx_tpu.models.conversion.convert_t5_state_dict`` (torch ``nn.Linear``
stores (out, in): kernels transpose on conversion).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from trlx_tpu.ops.attention import NEG_INF, dot_product_attention


@dataclass(frozen=True)
class T5Config:
    """Architecture hyperparameters (HF ``T5Config`` field names)."""

    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6
    num_decoder_layers: int = 6
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6
    feed_forward_proj: str = "relu"  # "relu" | "gated-gelu"
    tie_word_embeddings: bool = True
    decoder_start_token_id: int = 0
    dtype: str = "bfloat16"
    param_dtype: str = "float32"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "T5Config":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    @property
    def is_gated_act(self) -> bool:
        return "gated" in self.feed_forward_proj


# TP rules: attention and FF input projections shard outputs; output
# projections shard inputs (one activation all-reduce per sub-layer).
T5_PARTITION_RULES = [
    (r"shared/embedding", P(None, "tp")),
    (r"(SelfAttention|EncDecAttention)/(q|k|v)/kernel", P(None, "tp")),
    (r"(SelfAttention|EncDecAttention)/o/kernel", P("tp", None)),
    (r"DenseReluDense/(wi|wi_0|wi_1)/kernel", P(None, "tp")),
    (r"DenseReluDense/wo/kernel", P("tp", None)),
    (r"lm_head/kernel", P(None, "tp")),
]


class T5LayerNorm(nn.Module):
    """RMS norm: no mean subtraction, no bias, fp32 accumulation."""

    epsilon: float = 1e-6
    dtype: str = "bfloat16"
    param_dtype: str = "float32"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param(
            "weight", nn.initializers.ones, (x.shape[-1],), jnp.dtype(self.param_dtype)
        )
        xf = x.astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        xf = xf * jax.lax.rsqrt(var + self.epsilon)
        return (xf * scale).astype(jnp.dtype(self.dtype))


def relative_position_bucket(
    relative_position: jax.Array,
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> jax.Array:
    """T5's log-spaced relative position bucketing (jit-safe)."""
    ret = jnp.zeros_like(relative_position)
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class RelPosBias(nn.Module):
    """Relative attention bias embedding -> [1, H, Q, K] additive bias."""

    config: T5Config
    bidirectional: bool

    @nn.compact
    def __call__(self, q_positions: jax.Array, k_positions: jax.Array) -> jax.Array:
        cfg = self.config
        rel = k_positions[None, :] - q_positions[:, None]  # [Q, K]
        buckets = relative_position_bucket(
            rel,
            self.bidirectional,
            cfg.relative_attention_num_buckets,
            cfg.relative_attention_max_distance,
        )
        table = nn.Embed(
            cfg.relative_attention_num_buckets,
            cfg.num_heads,
            param_dtype=jnp.dtype(cfg.param_dtype),
            name="relative_attention_bias",
        )
        bias = table(buckets)  # [Q, K, H]
        return jnp.transpose(bias, (2, 0, 1))[None].astype(jnp.float32)


class T5Attention(nn.Module):
    config: T5Config

    def setup(self):
        cfg = self.config
        inner = cfg.num_heads * cfg.d_kv
        kw = dict(
            use_bias=False,
            dtype=jnp.dtype(cfg.dtype),
            param_dtype=jnp.dtype(cfg.param_dtype),
        )
        self.q = nn.Dense(inner, **kw)
        self.k = nn.Dense(inner, **kw)
        self.v = nn.Dense(inner, **kw)
        self.o = nn.Dense(cfg.d_model, **kw)

    def __call__(
        self,
        x: jax.Array,  # [B, T, D] (already layer-normed)
        kv_source: Optional[jax.Array] = None,  # cross-attn keys source
        bias: Optional[jax.Array] = None,  # additive [*, H or 1, Q, K]
        cache_kv: Optional[Dict[str, jax.Array]] = None,
        cache_index: Optional[jax.Array] = None,
        static_kv: Optional[Tuple[jax.Array, jax.Array]] = None,  # precomputed cross k,v
        learned_bias: bool = False,  # True when bias carries the rel-pos table
    ) -> Tuple[jax.Array, Optional[Dict[str, jax.Array]]]:
        cfg = self.config
        B, T, _ = x.shape
        inner = cfg.num_heads * cfg.d_kv

        q = self.q(x).reshape(B, T, cfg.num_heads, cfg.d_kv)
        if static_kv is not None:
            k, v = static_kv
            new_kv = None
        else:
            src = x if kv_source is None else kv_source
            S = src.shape[1]
            k = self.k(src).reshape(B, S, cfg.num_heads, cfg.d_kv)
            v = self.v(src).reshape(B, S, cfg.num_heads, cfg.d_kv)
            new_kv = None
            if cache_kv is not None:
                # shared cache write path (would make int8 a config flip
                # for seq2seq decode too; t5 currently ships bf16 only)
                from trlx_tpu.models.gpt2 import write_cache

                k, v, new_kv = write_cache(
                    cache_kv, k, v, cache_index, jnp.dtype(cfg.dtype)
                )

        # T5 attention is unscaled: pre-multiply q by sqrt(d_kv) to cancel
        # the 1/sqrt(d) inside the shared attention core.
        q = q * jnp.asarray(cfg.d_kv, q.dtype) ** 0.5
        out = dot_product_attention(q, k, v, bias, learned_bias=learned_bias)
        out = out.reshape(B, T, inner)
        return self.o(out), new_kv

    def project_kv(self, src: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """Precompute cross-attention K/V from encoder output (decode path)."""
        cfg = self.config
        B, S, _ = src.shape
        return (
            self.k(src).reshape(B, S, cfg.num_heads, cfg.d_kv),
            self.v(src).reshape(B, S, cfg.num_heads, cfg.d_kv),
        )


class T5FF(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        pdtype = jnp.dtype(cfg.param_dtype)
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=dtype, param_dtype=pdtype, name=name
        )
        if cfg.is_gated_act:
            # HF "gated-gelu" resolves to gelu_new (tanh approximation)
            h = nn.gelu(dense(cfg.d_ff, "wi_0")(x), approximate=True) * dense(
                cfg.d_ff, "wi_1"
            )(x)
        else:
            h = nn.relu(dense(cfg.d_ff, "wi")(x))
        return dense(cfg.d_model, "wo")(h)


class T5EncoderBlock(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x, bias):
        cfg = self.config
        ln = lambda name: T5LayerNorm(
            cfg.layer_norm_epsilon, cfg.dtype, cfg.param_dtype, name=name
        )
        h, _ = T5Attention(cfg, name="SelfAttention")(
            ln("ln_self")(x), bias=bias, learned_bias=True
        )
        x = x + h
        x = x + T5FF(cfg, name="DenseReluDense")(ln("ln_ff")(x))
        return x


class T5DecoderBlock(nn.Module):
    config: T5Config

    def setup(self):
        cfg = self.config
        ln = lambda: T5LayerNorm(cfg.layer_norm_epsilon, cfg.dtype, cfg.param_dtype)
        self.ln_self = ln()
        self.SelfAttention = T5Attention(cfg)
        self.ln_cross = ln()
        self.EncDecAttention = T5Attention(cfg)
        self.ln_ff = ln()
        self.DenseReluDense = T5FF(cfg)

    def __call__(
        self,
        x,
        self_bias,
        cross_bias,
        encoder_hidden=None,
        cache_kv=None,
        cache_index=None,
        cross_kv=None,
    ):
        h, new_kv = self.SelfAttention(
            self.ln_self(x), bias=self_bias,
            cache_kv=cache_kv, cache_index=cache_index, learned_bias=True,
        )
        x = x + h
        h, _ = self.EncDecAttention(
            self.ln_cross(x),
            kv_source=encoder_hidden,
            bias=cross_bias,
            static_kv=cross_kv,
        )
        x = x + h
        x = x + self.DenseReluDense(self.ln_ff(x))
        return x, new_kv

    def cross_kv(self, encoder_hidden):
        return self.EncDecAttention.project_kv(encoder_hidden)


class T5Model(nn.Module):
    """Encoder-decoder with explicit decode cache.

    Methods (all usable via ``apply(..., method=...)``):
    - ``__call__``: full training forward (teacher-forced decoder);
    - ``encode``: encoder only;
    - ``decode``: decoder with optional KV cache + precomputed cross-KV;
    - ``init_cross_kv``: per-layer cross-attention K/V from encoder output.
    """

    config: T5Config

    def setup(self):
        cfg = self.config
        self.shared = nn.Embed(
            cfg.vocab_size,
            cfg.d_model,
            param_dtype=jnp.dtype(cfg.param_dtype),
            name="shared",
        )
        self.enc_rel_bias = RelPosBias(cfg, bidirectional=True, name="enc_rel_bias")
        self.dec_rel_bias = RelPosBias(cfg, bidirectional=False, name="dec_rel_bias")
        self.enc_blocks = [
            T5EncoderBlock(cfg, name=f"enc_{i}") for i in range(cfg.num_layers)
        ]
        self.dec_blocks = [
            T5DecoderBlock(cfg, name=f"dec_{i}")
            for i in range(cfg.num_decoder_layers)
        ]
        self.enc_final_ln = T5LayerNorm(
            cfg.layer_norm_epsilon, cfg.dtype, cfg.param_dtype, name="enc_final_ln"
        )
        self.dec_final_ln = T5LayerNorm(
            cfg.layer_norm_epsilon, cfg.dtype, cfg.param_dtype, name="dec_final_ln"
        )
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Dense(
                cfg.vocab_size,
                use_bias=False,
                dtype=jnp.dtype(cfg.dtype),
                param_dtype=jnp.dtype(cfg.param_dtype),
                name="lm_head",
            )

    def encode(self, input_ids: jax.Array, attention_mask: Optional[jax.Array] = None):
        cfg = self.config
        T = input_ids.shape[1]
        x = self.shared(input_ids).astype(jnp.dtype(cfg.dtype))
        pos = jnp.arange(T)
        bias = self.enc_rel_bias(pos, pos)  # [1, H, T, T]
        if attention_mask is not None:
            bias = bias + jnp.where(
                attention_mask[:, None, None, :] > 0, 0.0, NEG_INF
            )
        for block in self.enc_blocks:
            x = block(x, bias)
        return self.enc_final_ln(x)

    def logits(self, hidden: jax.Array) -> jax.Array:
        cfg = self.config
        if cfg.tie_word_embeddings:
            # T5 1.0 rescales tied-head inputs by d_model**-0.5
            hidden = hidden * (cfg.d_model**-0.5)
            emb = self.shared.embedding.astype(hidden.dtype)
            return jnp.einsum(
                "btd,vd->btv", hidden, emb, preferred_element_type=jnp.float32
            )
        return self.lm_head(hidden).astype(jnp.float32)

    def init_cross_kv(self, encoder_hidden: jax.Array):
        return tuple(b.cross_kv(encoder_hidden) for b in self.dec_blocks)

    def decode(
        self,
        decoder_input_ids: jax.Array,  # [B, T]
        encoder_hidden: Optional[jax.Array] = None,
        encoder_mask: Optional[jax.Array] = None,
        decoder_mask: Optional[jax.Array] = None,  # [B, T] (training) / [B, C] (cache)
        cache: Optional[Tuple] = None,
        cache_index: Optional[jax.Array] = None,
        cross_kv: Optional[Tuple] = None,
    ):
        cfg = self.config
        B, T = decoder_input_ids.shape
        x = self.shared(decoder_input_ids).astype(jnp.dtype(cfg.dtype))

        if cache is None:
            q_pos = jnp.arange(T)
            k_pos = jnp.arange(T)
            causal = jnp.where(
                k_pos[None, :] <= q_pos[:, None], 0.0, NEG_INF
            )[None, None]
            self_bias = self.dec_rel_bias(q_pos, k_pos) + causal
            if decoder_mask is not None:
                self_bias = self_bias + jnp.where(
                    decoder_mask[:, None, None, :] > 0, 0.0, NEG_INF
                )
        else:
            C = cache[0]["k"].shape[1]
            q_pos = cache_index + jnp.arange(T)
            k_pos = jnp.arange(C)
            causal = jnp.where(
                k_pos[None, :] <= q_pos[:, None], 0.0, NEG_INF
            )[None, None]
            self_bias = self.dec_rel_bias(q_pos, k_pos) + causal
            if decoder_mask is not None:
                self_bias = self_bias + jnp.where(
                    decoder_mask[:, None, None, :] > 0, 0.0, NEG_INF
                )

        cross_bias = None
        if encoder_mask is not None:
            cross_bias = jnp.where(
                encoder_mask[:, None, None, :] > 0, 0.0, NEG_INF
            ).astype(jnp.float32)

        new_cache: List = []
        for i, block in enumerate(self.dec_blocks):
            x, new_kv = block(
                x,
                self_bias,
                cross_bias,
                encoder_hidden=encoder_hidden,
                cache_kv=cache[i] if cache is not None else None,
                cache_index=cache_index,
                cross_kv=cross_kv[i] if cross_kv is not None else None,
            )
            new_cache.append(new_kv)

        x = self.dec_final_ln(x)
        return {
            "logits": self.logits(x),
            "hidden": x,
            "cache": tuple(new_cache) if cache is not None else None,
        }

    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        decoder_input_ids: Optional[jax.Array] = None,
        decoder_attention_mask: Optional[jax.Array] = None,
    ):
        """Teacher-forced training forward; returns logits/hidden over the
        decoder sequence plus the encoder output."""
        encoder_hidden = self.encode(input_ids, attention_mask)
        out = self.decode(
            decoder_input_ids,
            encoder_hidden=encoder_hidden,
            encoder_mask=attention_mask,
            decoder_mask=decoder_attention_mask,
        )
        out["encoder_hidden"] = encoder_hidden
        return out


def init_t5_cache(config: T5Config, batch_size: int, capacity: int):
    """Fixed-capacity decoder self-attention KV buffers."""
    shape = (batch_size, capacity, config.num_heads, config.d_kv)
    dtype = jnp.dtype(config.dtype)
    return tuple(
        {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        for _ in range(config.num_decoder_layers)
    )


def shift_tokens_right(
    input_ids: jax.Array, pad_token_id: int, decoder_start_token_id: int
) -> jax.Array:
    """Teacher-forcing shift (reference `accelerate_ppo_model.py:18-25`)."""
    shifted = jnp.concatenate(
        [
            jnp.full_like(input_ids[:, :1], decoder_start_token_id),
            input_ids[:, :-1],
        ],
        axis=1,
    )
    return jnp.where(shifted == -100, pad_token_id, shifted)
