"""GPT-J causal LM (the reference's 6B PPO config, ``configs/ppo_gptj.yml``).

Architecture vs GPT-2: no position embeddings (rotary, interleaved
convention, applied to the first ``rotary_dim`` dims per head), attention
and MLP computed *in parallel* from one layernorm, bias-free q/k/v/out
projections, untied LM head with bias. Same call interface as
``GPT2Model`` so the PPO/ILQL trainers and samplers are family-agnostic.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from trlx_tpu.ops.attention import causal_dispatch, dot_product_attention
from trlx_tpu.ops.rotary import apply_rotary_interleaved, rotary_angles


@dataclass(frozen=True)
class GPTJConfig:
    vocab_size: int = 50400
    n_positions: int = 2048
    n_embd: int = 4096
    n_layer: int = 28
    n_head: int = 16
    rotary_dim: int = 64
    layer_norm_epsilon: float = 1e-5
    dtype: str = "bfloat16"
    param_dtype: str = "float32"
    # rollout KV-cache storage ("bfloat16" | "int8" | "auto"); see
    # models/gpt2.py::write_cache — decode is HBM-bound and the
    # cache is its dominant traffic, int8 halves it
    kv_cache_dtype: str = "bfloat16"

    def __post_init__(self):
        from trlx_tpu.models.gpt2 import validate_kv_cache_dtype

        validate_kv_cache_dtype(self.kv_cache_dtype)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "GPTJConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


GPTJ_PARTITION_RULES = [
    (r"wte/embedding", P(None, "tp")),
    (r"attn/(q_proj|k_proj|v_proj)/kernel", P(None, "tp")),
    (r"attn/out_proj/kernel", P("tp", None)),
    (r"mlp/fc_in/kernel", P(None, "tp")),
    (r"mlp/fc_out/kernel", P("tp", None)),
    (r"lm_head/kernel", P(None, "tp")),
]


class GPTJAttention(nn.Module):
    config: GPTJConfig

    @nn.compact
    def __call__(self, x, bias, position_ids, cache_kv=None, cache_index=None, causal=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        pdtype = jnp.dtype(cfg.param_dtype)
        B, T, D = x.shape
        head_dim = cfg.n_embd // cfg.n_head
        proj = lambda name: nn.Dense(
            cfg.n_embd, use_bias=False, dtype=dtype, param_dtype=pdtype, name=name
        )

        q = proj("q_proj")(x).reshape(B, T, cfg.n_head, head_dim)
        k = proj("k_proj")(x).reshape(B, T, cfg.n_head, head_dim)
        v = proj("v_proj")(x).reshape(B, T, cfg.n_head, head_dim)

        sin, cos = rotary_angles(position_ids, cfg.rotary_dim)
        q = apply_rotary_interleaved(q, sin, cos, cfg.rotary_dim)
        k = apply_rotary_interleaved(k, sin, cos, cfg.rotary_dim)

        new_kv = None
        if cache_kv is not None:
            from trlx_tpu.models.gpt2 import write_cache

            # bias width == attention view width (a prompt-only mask —
            # the chunked prefill — narrows the cache view to match)
            view_len = bias.shape[-1] if bias is not None else None
            k, v, new_kv = write_cache(
                cache_kv, k, v, cache_index, dtype, view_len=view_len
            )

        out = dot_product_attention(q, k, v, bias, causal=causal)
        out = out.reshape(B, T, cfg.n_embd)
        return proj("out_proj")(out), new_kv


class GPTJMLP(nn.Module):
    config: GPTJConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        pdtype = jnp.dtype(cfg.param_dtype)
        h = nn.Dense(4 * cfg.n_embd, dtype=dtype, param_dtype=pdtype, name="fc_in")(x)
        h = nn.gelu(h, approximate=True)
        return nn.Dense(cfg.n_embd, dtype=dtype, param_dtype=pdtype, name="fc_out")(h)


class GPTJBlock(nn.Module):
    config: GPTJConfig

    @nn.compact
    def __call__(self, x, bias, position_ids, cache_kv=None, cache_index=None, causal=False):
        cfg = self.config
        h = nn.LayerNorm(
            epsilon=cfg.layer_norm_epsilon, dtype=jnp.dtype(cfg.dtype), name="ln_1"
        )(x)
        attn_out, new_kv = GPTJAttention(cfg, name="attn")(
            h, bias, position_ids, cache_kv, cache_index, causal
        )
        mlp_out = GPTJMLP(cfg, name="mlp")(h)  # parallel residual branches
        return x + attn_out + mlp_out, new_kv


class GPTJModel(nn.Module):
    """Same interface as ``GPT2Model`` (incl. hydra hooks)."""

    config: GPTJConfig

    def setup(self):
        cfg = self.config
        pdtype = jnp.dtype(cfg.param_dtype)
        self.wte = nn.Embed(cfg.vocab_size, cfg.n_embd, param_dtype=pdtype, name="wte")
        self.h = [GPTJBlock(cfg, name=f"h_{i}") for i in range(cfg.n_layer)]
        self.ln_f = nn.LayerNorm(
            epsilon=cfg.layer_norm_epsilon, dtype=jnp.dtype(cfg.dtype), name="ln_f"
        )
        self.lm_head = nn.Dense(
            cfg.vocab_size,
            use_bias=True,
            dtype=jnp.dtype(cfg.dtype),
            param_dtype=pdtype,
            name="lm_head",
        )

    def logits(self, hidden: jax.Array) -> jax.Array:
        """LM head on (already ln_f-normalized) hidden states; float32."""
        return self.lm_head(hidden).astype(jnp.float32)

    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: Optional[jax.Array] = None,
        position_ids: Optional[jax.Array] = None,
        cache=None,
        cache_index=None,
        start_layer: int = 0,
        hidden_override: Optional[jax.Array] = None,
        capture_hidden_at: Optional[int] = None,
        compute_logits: bool = True,
    ):
        cfg = self.config
        T = input_ids.shape[1] if hidden_override is None else hidden_override.shape[1]

        if position_ids is None:
            if attention_mask is not None and cache is None:
                position_ids = jnp.clip(jnp.cumsum(attention_mask, axis=-1) - 1, 0, None)
            else:
                position_ids = jnp.broadcast_to(
                    jnp.arange(T)[None, :], (input_ids.shape[0], T)
                )
        else:
            position_ids = jnp.broadcast_to(position_ids, (input_ids.shape[0], T))

        if hidden_override is not None:
            x = hidden_override.astype(jnp.dtype(cfg.dtype))
        else:
            x = self.wte(input_ids).astype(jnp.dtype(cfg.dtype))

        bias, causal = causal_dispatch(T, cache, cache_index, attention_mask)

        new_cache: List = []
        branch_hidden = None
        for i in range(start_layer, cfg.n_layer):
            if capture_hidden_at is not None and i == capture_hidden_at:
                branch_hidden = x
            layer_cache = cache[i] if cache is not None else None
            x, new_kv = self.h[i](x, bias, position_ids, layer_cache, cache_index, causal)
            new_cache.append(new_kv)

        x = self.ln_f(x)
        out = {
            "logits": self.logits(x) if compute_logits else None,
            "hidden": x,
            "cache": tuple(new_cache) if cache is not None else None,
        }
        if capture_hidden_at is not None:
            out["branch_hidden"] = branch_hidden
        return out


def init_gptj_cache(config: GPTJConfig, batch_size: int, capacity: int):
    from trlx_tpu.models.gpt2 import kv_buffers

    return kv_buffers(
        config.n_layer, batch_size, capacity, config.n_head,
        config.n_embd // config.n_head, config.dtype,
        getattr(config, "kv_cache_dtype", "bfloat16"),
    )
