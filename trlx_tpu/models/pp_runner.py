"""Pipeline-parallel trunk forward for the GPT-2 family.

Integrates ``parallel/pipeline.py``'s GPipe primitive into the real model:
the full-sequence forwards the PPO update runs (policy ``response_forward``
and the frozen-ref scoring pass) route their transformer blocks through
``pipeline_apply`` over the mesh's ``pp`` axis, with embeddings and heads
running replicated over pp. This makes ``mesh: {dp: ..., pp: ...}`` a real
training capability rather than a standalone demo (the reference has no pp
at all — SURVEY §2.9 "PP: NO"; this is the beyond-parity axis).

Scope and composition:
- Stage s runs blocks ``[s*L/S, (s+1)*L/S)`` with an in-stage ``lax.scan``;
  activations hop stages via ``ppermute`` (GPipe schedule, differentiable).
- Param *residency* (at rest) follows the existing fsdp/tp partition
  rules. During the pipeline loop itself, stage params are all-gathered
  over fsdp at the shard_map boundary (`parallel/pipeline.py`): pp shards
  params/compute *across stages*; fsdp shards the at-rest copy and the
  optimizer state, not the running stage's working set.
- Autoregressive decode keeps the standard GSPMD sampler (a KV cache
  threaded through pipeline stages is a different schedule; decode under a
  pp mesh runs the plain forward with params replicated over pp).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from trlx_tpu.models.gpt2 import Block, GPT2Config, GPT2Model
from trlx_tpu.models.heads import MLPHead
from trlx_tpu.ops.attention import causal_dispatch
from trlx_tpu.parallel.pipeline import pipeline_apply, stack_stage_params


def supports_pp(model_config) -> bool:
    return isinstance(model_config, GPT2Config)


def _stack_stages(block_params, stages: int):
    """[L] per-block param trees -> leaves [S, L/S, ...] (stage-major)."""
    per = len(block_params) // stages
    stage_trees = [
        jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs, axis=0),
            *block_params[s * per : (s + 1) * per],
        )
        for s in range(stages)
    ]
    return stack_stage_params(stage_trees)


def pp_hidden_forward(
    config: GPT2Config,
    backbone_params,
    input_ids: jax.Array,  # [B, T]
    attention_mask: jax.Array,  # [B, T]
    mesh: Mesh,
    num_microbatches: int = 2,
) -> jax.Array:
    """Full-sequence causal trunk forward (embed -> pp blocks -> ln_f),
    numerically identical to ``GPT2Model.__call__`` with ``cache=None``.
    Embedding / ln_f / heads reuse the flax module methods (one definition)
    — only the block loop is replaced by the pipeline schedule."""
    S = mesh.shape["pp"]
    if config.n_layer % S:
        raise ValueError(
            f"n_layer={config.n_layer} must divide into pp={S} stages"
        )
    backbone = GPT2Model(config)
    position_ids = jnp.clip(jnp.cumsum(attention_mask, axis=-1) - 1, 0, None)
    x = backbone.apply(
        {"params": backbone_params}, input_ids, position_ids,
        method=GPT2Model.embed,
    )
    bias, causal = causal_dispatch(
        input_ids.shape[1], None, None, attention_mask
    )

    stacked = _stack_stages(
        [backbone_params[f"h_{i}"] for i in range(config.n_layer)], S
    )
    block = Block(config)

    def stage_fn(stage_params, h, bias_mb):
        def body(h, p):
            h, _ = block.apply({"params": p}, h, bias_mb, causal=causal)
            return h, None

        h, _ = jax.lax.scan(body, h, stage_params)
        return h

    h = pipeline_apply(
        stage_fn, stacked, x, mesh,
        num_microbatches=num_microbatches, aux=bias,
    )
    return backbone.apply(
        {"params": backbone_params}, h, method=lambda m, v: m.ln_f(v)
    )


def _logits(config: GPT2Config, backbone_params, hidden: jax.Array):
    """Tied LM head on (already-sliced) hidden states via the module's own
    definition (``GPT2Model.logits``)."""
    return GPT2Model(config).apply(
        {"params": backbone_params}, hidden, method=GPT2Model.logits
    )


def pp_response_forward(
    config: GPT2Config,
    params,  # CausalLMWithValueHead params: {"transformer", "v_head"}
    input_ids: jax.Array,
    attention_mask: jax.Array,
    query_length: int,
    mesh: Mesh,
    num_microbatches: int = 2,
):
    """pp counterpart of ``CausalLMWithValueHead.response_forward``:
    (logits, values) over the response-predicting positions Q-1..Q+R-2."""
    h = pp_hidden_forward(
        config, params["transformer"], input_ids, attention_mask,
        mesh, num_microbatches,
    )
    hs = h[:, query_length - 1 : -1]
    v_head = MLPHead(
        config.n_embd, 1, dtype=config.dtype, param_dtype=config.param_dtype
    )
    values = v_head.apply({"params": params["v_head"]}, hs)[..., 0]
    return _logits(config, params["transformer"], hs), values


def pp_ref_logits(
    config: GPT2Config,
    backbone_params,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    query_length: int,
    mesh: Mesh,
    num_microbatches: int = 2,
) -> jax.Array:
    """Frozen-reference logits over response-predicting positions (the
    full-copy ref path; hydra's shared-trunk branch is not offered under
    pp — the trunk capture point sits mid-pipeline)."""
    h = pp_hidden_forward(
        config, backbone_params, input_ids, attention_mask,
        mesh, num_microbatches,
    )
    return _logits(config, backbone_params, h[:, query_length - 1 : -1])
