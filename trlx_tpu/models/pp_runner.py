"""Pipeline-parallel trunk forward for the causal-LM families.

Integrates ``parallel/pipeline.py``'s GPipe primitive into the real models:
the full-sequence forwards the PPO update runs (policy ``response_forward``
and the frozen-ref scoring pass) route their transformer blocks through
``pipeline_apply`` over the mesh's ``pp`` axis, with embeddings and heads
running replicated over pp. This makes ``mesh: {dp: ..., pp: ...}`` a real
training capability rather than a standalone demo (the reference has no pp
at all — SURVEY §2.9 "PP: NO"; this is a beyond-parity axis).

Family coverage (round 3 widened from GPT-2-only): **gpt2, gptj, gpt_neo,
gpt_neox** — every causal family. The per-family differences ride a small
kit: rotary families (gptj/neox) thread ``position_ids`` into each block
via the schedule's aux tree; gpt_neo's alternating global/local (sliding
window) layers select between two explicit biases with a per-layer flag
scanned alongside the stage params. MoE (`gpt2_moe`) stays excluded — its
per-layer param structure is non-uniform (router/experts on MoE layers
only), so stage stacking does not apply. The seq2seq (T5) family has its
own pipeline section below (``pp_t5_forward``): both trunk stacks run the
schedule back to back, with the per-stack rel-pos bias and the encoder
output riding the aux tree.

Scope and composition:
- Stage s runs blocks ``[s*L/S, (s+1)*L/S)`` with an in-stage ``lax.scan``;
  activations hop stages via ``ppermute`` (GPipe schedule, differentiable).
- Param *residency* (at rest) follows the existing fsdp/tp partition
  rules. During the pipeline loop itself, stage params are all-gathered
  over fsdp at the shard_map boundary (`parallel/pipeline.py`): pp shards
  params/compute *across stages*; fsdp shards the at-rest copy and the
  optimizer state, not the running stage's working set.
- Autoregressive decode runs the SAME pipeline schedule with
  stage-resident KV caches: the sampler's cache is layer-major
  ``[L, B, C, H, Dh]`` sharded over pp (bf16 or int8 value+scale leaves),
  so each device holds only its stage's layers and cache during rollouts
  (``pp_cached_hidden`` / ``make_pp_sampler_apply`` below) — no replicated
  full-model copy.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from trlx_tpu.models.gpt2 import Block, GPT2Config, GPT2Model
from trlx_tpu.models.heads import MLPHead
from trlx_tpu.models.registry import hidden_size_of, n_heads_of, num_layers_of
from trlx_tpu.ops.attention import (
    causal_bias,
    combine_biases,
    padding_bias,
)
from trlx_tpu.parallel.pipeline import (
    pipeline_apply,
    spmd_stack,
    stack_stage_params,
)


@dataclass(frozen=True)
class _PPKit:
    """Family adapter for the pipeline schedule."""

    backbone_cls: Any
    block_cls: Any
    takes_positions: bool  # block signature threads position_ids (rotary)
    has_wpe: bool  # embed = wte + wpe (else wte only)
    windowed: bool  # per-layer global/local band attention (gpt_neo)


def _pp_kit(config) -> Optional[_PPKit]:
    from trlx_tpu.models.gpt_neo import GPTNeoBlock, GPTNeoConfig, GPTNeoModel
    from trlx_tpu.models.gptj import GPTJBlock, GPTJConfig, GPTJModel
    from trlx_tpu.models.neox import NeoXBlock, NeoXConfig, NeoXModel

    if isinstance(config, GPT2Config):
        return _PPKit(GPT2Model, Block, False, True, False)
    if isinstance(config, GPTJConfig):
        return _PPKit(GPTJModel, GPTJBlock, True, False, False)
    if isinstance(config, GPTNeoConfig):
        return _PPKit(GPTNeoModel, GPTNeoBlock, False, True, True)
    if isinstance(config, NeoXConfig):
        return _PPKit(NeoXModel, NeoXBlock, True, False, False)
    return None


def supports_pp(model_config) -> bool:
    return _pp_kit(model_config) is not None


def _stack_stages(block_params, stages: int, virtual: int = 1):
    """[L] per-block param trees -> leaves [S, L/S, ...] (stage-major), or
    [S, v, L/(S·v), ...] when ``virtual > 1`` (interleaved: chunk
    c = lap·S + d on device d — round-robin layer placement)."""
    # per-layer stacking goes through spmd_stack, never jnp.stack: these
    # arrays feed shard_map P("pp") in_specs, where XLA's SPMD partitioner
    # miscompiles a stack/concatenate operand under jit on any mesh with
    # a second size>1 axis (tools/pp_miscompile_repro.py)
    groups = stages * virtual
    per = len(block_params) // groups
    group_trees = [
        jax.tree_util.tree_map(
            spmd_stack, *block_params[g * per : (g + 1) * per]
        )
        for g in range(groups)
    ]
    if virtual > 1:
        from trlx_tpu.parallel.pipeline import stack_stage_params_interleaved

        return stack_stage_params_interleaved(group_trees, stages, virtual)
    return stack_stage_params(group_trees)


def _local_flags(config, stages: int, virtual: int = 1) -> Optional[jax.Array]:
    """gpt_neo per-layer local-attention flags, stage-stacked like params."""
    types = config.layer_types
    flags = [jnp.asarray(t == "local") for t in types]
    return _stack_stages(flags, stages, virtual)


def _embed(kit: _PPKit, config, backbone_params, input_ids, position_ids):
    """Token (+ absolute position) embedding via the family's own tables;
    per-table rounding to the compute dtype (matches the backbones)."""
    dtype = jnp.dtype(config.dtype)
    backbone = kit.backbone_cls(config)
    if kit.has_wpe:
        return backbone.apply(
            {"params": backbone_params}, input_ids, position_ids,
            method=lambda m, i, p: m.wte(i).astype(dtype)
            + m.wpe(p).astype(dtype),
        )
    return backbone.apply(
        {"params": backbone_params}, input_ids,
        method=lambda m, i: m.wte(i).astype(dtype),
    )


def _ln_f(kit: _PPKit, config, backbone_params, h):
    return kit.backbone_cls(config).apply(
        {"params": backbone_params}, h, method=lambda m, v: m.ln_f(v)
    )


def _logits(kit: _PPKit, config, backbone_params, hidden: jax.Array):
    """LM head on (already-sliced) hidden states via the family's own
    ``logits`` definition (tied wte or separate lm_head)."""
    cls = kit.backbone_cls
    return cls(config).apply(
        {"params": backbone_params}, hidden, method=cls.logits
    )


def _neo_local_bias(config, T, kv_len, offset, pad):
    from trlx_tpu.models.gpt_neo import local_causal_bias

    return combine_biases(
        local_causal_bias(T, kv_len, config.window_size, offset=offset), pad
    )


def _stage_body(kit: _PPKit, block, aux_mb, causal: bool, cached: bool):
    """One scan body serving both schedules: unpack per-layer xs (params
    [+ cache slice] [+ local flag]), select the bias (windowed families pick
    per layer between aux "local" and "global"), thread rotary positions.
    Cached mode reads ``aux_mb["idx"]`` as the cache write index."""

    def body(h, xs):
        if kit.windowed:
            (p, *rest, flag) = xs
            bias = jnp.where(flag, aux_mb["local"], aux_mb["global"])
        else:
            (p, *rest) = xs if cached else (xs,)
            bias = aux_mb["global"]
        args = (h, bias) + ((aux_mb["pos"],) if kit.takes_positions else ())
        if cached:
            return block.apply(
                {"params": p}, *args, cache_kv=rest[0],
                cache_index=aux_mb["idx"], causal=False,
            )
        h, _ = block.apply({"params": p}, *args, causal=causal)
        return h, None

    return body


def _run_schedule(stage_fn, stage_tree, x, mesh, num_microbatches, aux,
                  virtual_stages, remat):
    """One dispatch point for the plain train schedule: autodiffed GPipe /
    interleaved (v > 1) or the rematerialized backward. Centralizes the
    remat-vs-v guard so every caller fails the same way."""
    if remat:
        if virtual_stages > 1:
            raise NotImplementedError(
                "pp_remat runs the v=1 schedule; drop pp_virtual_stages "
                "or pp_remat (the two memory/bubble trades do not "
                "compose yet)"
            )
        from trlx_tpu.parallel.pipeline import pipeline_apply_remat

        return pipeline_apply_remat(
            stage_fn, stage_tree, x, mesh,
            num_microbatches=num_microbatches, aux=aux,
        )
    return pipeline_apply(
        stage_fn, stage_tree, x, mesh,
        num_microbatches=num_microbatches, aux=aux,
        virtual_stages=virtual_stages,
    )


def pp_hidden_forward(
    config,
    backbone_params,
    input_ids: jax.Array,  # [B, T]
    attention_mask: jax.Array,  # [B, T]
    mesh: Mesh,
    num_microbatches: int = 2,
    virtual_stages: int = 1,
    capture_layer: int = None,
    capture_only: bool = False,
    remat: bool = False,
) -> jax.Array:
    """Full-sequence causal trunk forward (embed -> pp blocks -> ln_f),
    numerically identical to the family backbone's ``__call__`` with
    ``cache=None``. Embedding / ln_f / heads reuse the flax module methods
    (one definition) — only the block loop is replaced by the pipeline
    schedule. Rotary position_ids and gpt_neo's per-layer band biases ride
    the schedule's aux tree. ``virtual_stages > 1`` runs the interleaved
    schedule (`train.pp_virtual_stages`): bubble shrinks ~v× at the cost
    of v× more ppermute hops (`pipeline_span_layer_units`).
    ``capture_layer=k`` (v=1, k on a stage boundary) additionally returns
    the activation entering block k — the hydra branch point (the non-pp
    backbones' ``capture_hidden_at``); the return becomes
    ``(h_after_ln_f, captured)``."""
    kit = _pp_kit(config)
    if kit is None:
        raise NotImplementedError(
            f"pp is not available for {type(config).__name__}"
        )
    S = mesh.shape["pp"]
    v = virtual_stages
    L = num_layers_of(config)
    if L % (S * v):
        raise ValueError(
            f"n_layer={L} must divide into pp={S} stages x {v} virtual"
        )
    B, T = input_ids.shape
    position_ids = jnp.clip(jnp.cumsum(attention_mask, axis=-1) - 1, 0, None)
    x = _embed(kit, config, backbone_params, input_ids, position_ids)

    pad = padding_bias(attention_mask)
    if kit.windowed:
        # the causal FLAG cannot vary per scanned layer, so the windowed
        # family uses explicit biases for all layers (same mask values)
        aux = {
            "global": jnp.broadcast_to(
                combine_biases(causal_bias(T, T), pad),
                (B, 1, T, T),
            ),
            "local": jnp.broadcast_to(
                _neo_local_bias(config, T, T, 0, pad), (B, 1, T, T)
            ),
        }
        causal = False
    else:
        aux = {"global": pad}
        causal = True
    if kit.takes_positions:
        aux["pos"] = position_ids

    stacked = _stack_stages(
        [backbone_params[f"h_{i}"] for i in range(L)], S, v
    )
    flags = _local_flags(config, S, v) if kit.windowed else None
    block = kit.block_cls(config)

    def stage_fn(stage_params, h, aux_mb):
        params, lflags = stage_params if kit.windowed else (stage_params, None)
        body = _stage_body(kit, block, aux_mb, causal, cached=False)
        xs = (params, lflags) if kit.windowed else params
        h, _ = jax.lax.scan(body, h, xs)
        return h

    capture_stage = None
    if capture_layer is not None:
        chunk = L // S
        if capture_layer % chunk:
            raise NotImplementedError(
                f"hydra branch point at layer {capture_layer} does not sit "
                f"on a stage boundary (stage size {chunk}); choose "
                f"num_layers_unfrozen so L - unfrozen is a multiple of L/pp"
            )
        capture_stage = capture_layer // chunk

    stage_tree = (stacked, flags) if kit.windowed else stacked
    if capture_stage is not None:
        if remat:
            raise NotImplementedError(
                "pp_remat has no hydra capture; use the autodiffed schedule"
            )
        res = pipeline_apply(
            stage_fn, stage_tree, x, mesh,
            num_microbatches=num_microbatches, aux=aux, virtual_stages=v,
            capture_stage=capture_stage, capture_only=capture_only,
        )
    else:
        res = _run_schedule(
            stage_fn, stage_tree, x, mesh, num_microbatches, aux, v, remat
        )
    if capture_stage is None:
        return _ln_f(kit, config, backbone_params, res)
    h, caps = res
    if capture_only:
        # the schedule stopped at the capture; h never finished (stages
        # >= k did not run) — return only the branch activation
        return None, caps
    return _ln_f(kit, config, backbone_params, h), caps


def pp_response_forward(
    config,
    params,  # CausalLMWithValueHead params: {"transformer", "v_head"}
    input_ids: jax.Array,
    attention_mask: jax.Array,
    query_length: int,
    mesh: Mesh,
    num_microbatches: int = 2,
    virtual_stages: int = 1,
    remat: bool = False,
):
    """pp counterpart of ``CausalLMWithValueHead.response_forward``:
    (logits, values) over the response-predicting positions Q-1..Q+R-2.
    ``remat=True`` routes the trunk through the rematerialized-backward
    schedule (`pipeline_apply_remat`) — stage inputs are the only saved
    residuals, cutting the update's peak activation memory."""
    kit = _pp_kit(config)
    h = pp_hidden_forward(
        config, params["transformer"], input_ids, attention_mask,
        mesh, num_microbatches, virtual_stages, remat=remat,
    )
    hs = h[:, query_length - 1 : -1]
    v_head = MLPHead(
        hidden_size_of(config), 1, dtype=config.dtype,
        param_dtype=config.param_dtype,
    )
    values = v_head.apply({"params": params["v_head"]}, hs)[..., 0]
    return _logits(kit, config, params["transformer"], hs), values


def pp_ref_logits(
    config,
    backbone_params,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    query_length: int,
    mesh: Mesh,
    num_microbatches: int = 2,
    virtual_stages: int = 1,
) -> jax.Array:
    """Frozen-reference logits over response-predicting positions (the
    full-copy ref path; the hydra shared-trunk variant is
    :func:`pp_hydra_ref_logits`)."""
    kit = _pp_kit(config)
    h = pp_hidden_forward(
        config, backbone_params, input_ids, attention_mask,
        mesh, num_microbatches, virtual_stages,
    )
    return _logits(kit, config, backbone_params, h[:, query_length - 1 : -1])


def pp_hydra_ref_logits(
    config,
    policy_backbone_params,
    ref_params,  # hydra subset: top blocks + ln_f + head tables
    input_ids: jax.Array,
    attention_mask: jax.Array,
    query_length: int,
    branch_start: int,
    mesh: Mesh,
    num_microbatches: int = 2,
) -> jax.Array:
    """Hydra shared-trunk KL reference under pp (`ppo_models.py:505-558`).

    The frozen trunk activation at the branch point is captured from the
    policy trunk's OWN pipeline schedule (the input of the stage owning
    block ``branch_start`` — a stage boundary, enforced by
    ``pp_hidden_forward``), then the small frozen branch (the top
    ``L - branch_start`` blocks + ln_f + LM head from ``ref_params``) runs
    replicated over pp — exactly the non-pp hydra semantics
    (``capture_hidden_at`` + ``start_layer``/``hidden_override``), with
    the branch too small to be worth pipelining."""
    kit = _pp_kit(config)
    L = num_layers_of(config)
    # capture_only: the schedule stops once the last microbatch reaches
    # the branch stage — the frozen top stages are not re-run for a result
    # nobody reads (they'd cost more than the full-copy ref otherwise)
    _, x = pp_hidden_forward(
        config, policy_backbone_params, input_ids, attention_mask,
        mesh, num_microbatches, capture_layer=branch_start,
        capture_only=True,
    )
    position_ids = jnp.clip(jnp.cumsum(attention_mask, axis=-1) - 1, 0, None)
    pad = padding_bias(attention_mask)
    block = kit.block_cls(config)
    types = config.layer_types if kit.windowed else None
    T = input_ids.shape[1]
    for i in range(branch_start, L):
        if kit.windowed and types[i] == "local":
            bias, causal = _neo_local_bias(config, T, T, 0, pad), False
        else:
            bias, causal = pad, True
        args = (x, bias) + ((position_ids,) if kit.takes_positions else ())
        x, _ = block.apply(
            {"params": ref_params[f"h_{i}"]}, *args, causal=causal
        )
    x = _ln_f(kit, config, ref_params, x)
    return _logits(kit, config, ref_params, x[:, query_length - 1 : -1])


# ------------------------- seq2seq (T5) pipeline ------------------------- #


def supports_pp_seq2seq(model_config) -> bool:
    from trlx_tpu.models.t5 import T5Config

    return isinstance(model_config, T5Config)


def _pp_t5_encode(
    config,
    t5_params,
    input_ids,
    attention_mask,
    mesh: Mesh,
    num_microbatches: int,
    enc_stacked=None,
    virtual_stages: int = 1,
    remat: bool = False,
):
    """Pipelined encoder pass (embed → rel-pos bias + mask → schedule →
    final LN), numerically identical to ``T5Model.encode``. ONE definition
    shared by the train forward (`pp_t5_forward`) and the rollout sampler
    (`make_pp_seq2seq_sampler_fns`) — hand-synced copies of a schedule
    invite silent rollout-vs-update divergence. ``enc_stacked`` lets the
    sampler pass blocks pre-stacked once per invocation."""
    from trlx_tpu.models.t5 import T5EncoderBlock, T5Model
    from trlx_tpu.ops.attention import NEG_INF

    backbone = T5Model(config)
    dtype = jnp.dtype(config.dtype)
    B, T_enc = input_ids.shape

    def bb(fn, *args):
        return backbone.apply({"params": t5_params}, *args, method=fn)

    x = bb(lambda m, i: m.shared(i).astype(dtype), input_ids)
    pos = jnp.arange(T_enc)
    enc_bias = bb(lambda m, q, k: m.enc_rel_bias(q, k), pos, pos)
    if attention_mask is not None:
        enc_bias = enc_bias + jnp.where(
            attention_mask[:, None, None, :] > 0, 0.0, NEG_INF
        )
    enc_bias = jnp.broadcast_to(enc_bias, (B,) + enc_bias.shape[1:])
    if enc_stacked is None:
        enc_stacked = _stack_stages(
            [t5_params[f"enc_{i}"] for i in range(config.num_layers)],
            mesh.shape["pp"], virtual_stages,
        )
    enc_block = T5EncoderBlock(config)

    def enc_stage(stage_params, h, aux_mb):
        def body(h, p):
            return enc_block.apply({"params": p}, h, aux_mb["bias"]), None

        h, _ = jax.lax.scan(body, h, stage_params)
        return h

    x = _run_schedule(
        enc_stage, enc_stacked, x, mesh, num_microbatches,
        {"bias": enc_bias}, virtual_stages, remat,
    )
    return bb(lambda m, v_: m.enc_final_ln(v_), x)


def pp_t5_forward(
    config,
    backbone_params,  # T5Model params ("t5" subtree)
    input_ids: jax.Array,  # [B, S_enc]
    attention_mask: jax.Array,  # [B, S_enc]
    decoder_input_ids: jax.Array,  # [B, T]
    decoder_attention_mask: jax.Array,  # [B, T]
    mesh: Mesh,
    num_microbatches: int = 2,
    virtual_stages: int = 1,
    remat: bool = False,
):
    """Teacher-forced enc→dec forward with BOTH stacks' blocks pipelined
    over pp (two schedules back to back), numerically identical to
    ``T5Model.__call__`` (`models/t5.py:431-448` — the fork's policy model,
    `ppo_models.py:607-655`). Embeddings, the learned rel-pos bias tables,
    final LayerNorms, and the LM head run replicated over pp; each stack's
    shared bias tensor is computed once outside the schedule and rides the
    aux tree (batch-leading), so gradient flows to the rel-pos embeddings
    through aux. The encoder output rides the decoder schedule's aux the
    same way (every device holds its batch shard).

    ``virtual_stages > 1`` (round 4): both stacks run the interleaved
    schedule — each device holds v round-robin layer chunks per stack, the
    fill/drain bubble shrinks ~v× per stack (the seq2seq path pays TWO
    schedules per forward, so the win applies twice)."""
    from trlx_tpu.models.t5 import T5DecoderBlock, T5EncoderBlock, T5Model
    from trlx_tpu.ops.attention import NEG_INF

    S = mesh.shape["pp"]
    v = virtual_stages
    L_enc, L_dec = config.num_layers, config.num_decoder_layers
    if L_enc % (S * v) or L_dec % (S * v):
        raise ValueError(
            f"num_layers={L_enc} and num_decoder_layers={L_dec} must both "
            f"divide into pp={S} stages x {v} virtual"
        )
    backbone = T5Model(config)
    dtype = jnp.dtype(config.dtype)
    B, T_enc = input_ids.shape

    def bb(fn, *args):
        return backbone.apply({"params": backbone_params}, *args, method=fn)

    # --- encoder stack: ONE pipelined-encoder definition shared with the
    # rollout sampler (`_pp_t5_encode`) ---
    encoder_hidden = _pp_t5_encode(
        config, backbone_params, input_ids, attention_mask, mesh,
        num_microbatches, virtual_stages=v, remat=remat,
    )

    # --- decoder stack (bias construction mirrors T5Model.decode) ---
    T = decoder_input_ids.shape[1]
    y = bb(lambda m, i: m.shared(i).astype(dtype), decoder_input_ids)
    q_pos = jnp.arange(T)
    k_pos = jnp.arange(T)
    causal = jnp.where(k_pos[None, :] <= q_pos[:, None], 0.0, NEG_INF)[
        None, None
    ]
    self_bias = bb(lambda m, q, k: m.dec_rel_bias(q, k), q_pos, k_pos) + causal
    if decoder_attention_mask is not None:
        self_bias = self_bias + jnp.where(
            decoder_attention_mask[:, None, None, :] > 0, 0.0, NEG_INF
        )
    self_bias = jnp.broadcast_to(self_bias, (B,) + self_bias.shape[1:])
    if attention_mask is not None:
        cross_bias = jnp.where(
            attention_mask[:, None, None, :] > 0, 0.0, NEG_INF
        ).astype(jnp.float32)
    else:  # unmasked cross-attention, as T5Model.decode's None path
        cross_bias = jnp.zeros((B, 1, 1, T_enc), jnp.float32)
    dec_stacked = _stack_stages(
        [backbone_params[f"dec_{i}"] for i in range(L_dec)], S, v
    )
    dec_block = T5DecoderBlock(config)

    def dec_stage(stage_params, h, aux_mb):
        def body(h, p):
            h, _ = dec_block.apply(
                {"params": p}, h, aux_mb["sb"], aux_mb["cb"],
                encoder_hidden=aux_mb["eh"],
            )
            return h, None

        h, _ = jax.lax.scan(body, h, stage_params)
        return h

    y = _run_schedule(
        dec_stage, dec_stacked, y, mesh, num_microbatches,
        {"sb": self_bias, "cb": cross_bias, "eh": encoder_hidden}, v, remat,
    )
    hidden = bb(lambda m, v_: m.dec_final_ln(v_), y)
    logits = bb(T5Model.logits, hidden)
    return {"logits": logits, "hidden": hidden}


def pp_t5_response_forward(
    config,
    params,  # T5WithValueHead params: {"t5", "v_head"}
    input_ids,
    attention_mask,
    decoder_input_ids,
    decoder_attention_mask,
    mesh: Mesh,
    num_microbatches: int = 2,
    virtual_stages: int = 1,
    remat: bool = False,
):
    """(logits, values) — the seq2seq PPO update's policy forward with the
    trunk stacks pipelined; the value head reads decoder hidden states
    (`ppo_models.py:638-641`) replicated over pp."""
    out = pp_t5_forward(
        config, params["t5"], input_ids, attention_mask,
        decoder_input_ids, decoder_attention_mask, mesh, num_microbatches,
        virtual_stages=virtual_stages, remat=remat,
    )
    v_head = MLPHead(
        config.d_model, 1, dtype=config.dtype, param_dtype=config.param_dtype
    )
    values = v_head.apply({"params": params["v_head"]}, out["hidden"])[..., 0]
    return out["logits"], values


def pp_t5_ref_logits(
    config,
    ref_params,  # T5Model params (full frozen copy — the fork's ref path)
    input_ids,
    attention_mask,
    decoder_input_ids,
    decoder_attention_mask,
    mesh: Mesh,
    num_microbatches: int = 2,
    virtual_stages: int = 1,
) -> jax.Array:
    """Frozen-reference logits with the trunk stacks pipelined (the fork
    uses a full frozen copy for T5 — `ppo_orchestrator.py:41-43`)."""
    return pp_t5_forward(
        config, ref_params, input_ids, attention_mask,
        decoder_input_ids, decoder_attention_mask, mesh, num_microbatches,
        virtual_stages=virtual_stages,
    )["logits"]


def pp_ilql_forward(
    config,
    params,  # CausalLMWithILQLHeads params: {"transformer", "heads"}
    input_ids: jax.Array,
    attention_mask: jax.Array,
    actions_ixs: Optional[jax.Array],
    states_ixs: Optional[jax.Array],
    mesh: Mesh,
    num_microbatches: int = 2,
    two_qs: bool = True,
    virtual_stages: int = 1,
    remat: bool = False,
):
    """pp counterpart of ``CausalLMWithILQLHeads.__call__`` (no cache):
    trunk blocks through the GPipe schedule; logits and the Q/V heads run
    replicated over pp on the gathered positions. Returns the same dict
    the flax module's forward does (`models/heads.py`)."""
    from trlx_tpu.models.heads import ILQLHeads

    kit = _pp_kit(config)
    h = pp_hidden_forward(
        config, params["transformer"], input_ids, attention_mask,
        mesh, num_microbatches, virtual_stages, remat=remat,
    )
    logits = _logits(kit, config, params["transformer"], h)
    action_hidden = (
        jnp.take_along_axis(h, actions_ixs[..., None], axis=1)
        if actions_ixs is not None
        else h
    )
    state_hidden = (
        jnp.take_along_axis(h, states_ixs[..., None], axis=1)
        if states_ixs is not None
        else h
    )
    qs, vs = ILQLHeads(config, two_qs).apply(
        {"params": params["heads"]}, action_hidden, state_hidden
    )
    return {
        "logits": logits,
        "qs": qs,
        "vs": vs,
        "action_hidden": action_hidden,
    }


def pp_slice_logits(config, backbone_params, hidden: jax.Array):
    """Family LM head on (already-sliced) hidden states — public wrapper
    for pp callers that slice before the head (`GPT2Model.logits`-class
    methods; the full [B, T, vocab] tensor is the most expensive
    intermediate)."""
    return _logits(_pp_kit(config), config, backbone_params, hidden)


def pp_decode_kit(config, mesh: Mesh):
    """The pp decode wiring both trainers share: ``(init_cache_fn,
    cache_sharding)`` for ``make_sampler`` — layer-major stage-resident
    buffers sharded ``P(pp, batch)``. One definition so a layout change
    cannot silently diverge the PPO and ILQL rollout paths."""
    import functools

    from jax.sharding import NamedSharding, PartitionSpec

    from trlx_tpu.parallel.mesh import BATCH_AXES

    return (
        functools.partial(pp_init_cache, config),
        NamedSharding(mesh, PartitionSpec("pp", BATCH_AXES)),
    )


# --------------------------- pp rollout decode --------------------------- #
#
# Decode under a pp mesh does not replicate the full model per device. The
# sampler's KV cache becomes layer-major [L, B, C, H, Dh] sharded
# P(pp, (dp, fsdp)) — each device holds the cache AND compute of its own
# stage's L/S layers only — and every sampler forward (prefill + each decode
# token) runs the GPipe schedule with the cache resident in the stages
# (`parallel/pipeline.py::pipeline_apply_cached`). Embedding, ln_f, LM head,
# and the value head stay replicated over pp (they are a small fraction of
# weights and need the full batch anyway).


def pp_init_cache(config, batch_size: int, capacity: int):
    """Layer-major KV buffers for pp decode: ``{"k","v"}: [L, B, C, H, Dh]``
    (vs the GSPMD sampler's per-layer tuple). ``kv_cache_dtype="int8"``
    composes: value+scale leaves, stage-sliced and microbatch-sliced like
    any other cache leaf (`write_cache` keys on the ``k_scale`` entry, so
    the per-layer dict the stage scan hands to the block is already in the
    quantized layout)."""
    L = num_layers_of(config)
    H = n_heads_of(config)
    head_dim = hidden_size_of(config) // H
    shape = (L, batch_size, capacity, H, head_dim)
    from trlx_tpu.models.gpt2 import resolve_kv_cache_dtype

    kv_dtype = resolve_kv_cache_dtype(
        getattr(config, "kv_cache_dtype", "bfloat16"), capacity
    )
    if kv_dtype == "int8":
        sshape = shape[:-1] + (1,)
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(sshape, jnp.bfloat16),
            "v_scale": jnp.zeros(sshape, jnp.bfloat16),
        }
    if kv_dtype != "bfloat16":
        # mirror kv_buffers: a future cache dtype (e.g. fp8) must fail loudly
        # here rather than silently allocating bf16 stage buffers
        raise ValueError(
            f"kv_cache_dtype={kv_dtype!r} has no pp stage-resident layout yet"
        )
    dtype = jnp.dtype(config.dtype)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def pp_stack_sampler_params(config, mesh: Mesh, params):
    """Pre-stack the trunk blocks for the pp sampler, ONCE per sampler
    invocation (outside the decode scan): the jnp.stack of every layer and
    the regather to P('pp') residency are loop-invariant, and leaving them
    inside the per-token apply would rely on XLA hoisting them out of the
    while-loop body. Returns the packed params pytree the
    ``make_pp_sampler_apply`` closure expects."""
    from jax.sharding import NamedSharding, PartitionSpec

    S = mesh.shape["pp"]
    stacked = _stack_stages(
        [params["transformer"][f"h_{i}"] for i in range(num_layers_of(config))],
        S,
    )
    stacked = jax.tree_util.tree_map(
        lambda p: jax.lax.with_sharding_constraint(
            p, NamedSharding(mesh, PartitionSpec("pp"))
        ),
        stacked,
    )
    # pass every head tree through untouched (PPO: v_head; ILQL: heads)
    return {**params, "stacked_blocks": stacked}


def pp_cached_hidden(
    config,
    backbone_params,
    input_ids: jax.Array,  # [B, T]
    attention_mask: jax.Array,  # [B, C] cache-validity mask
    position_ids: jax.Array,  # [B, T]
    cache,  # pp_init_cache layout
    cache_index,
    mesh: Mesh,
    num_microbatches: int = 2,
    stacked=None,  # pre-stacked blocks (pp_stack_sampler_params)
):
    """(hidden after ln_f, new cache) for a cached forward (prefill T=Q or
    decode T=1) with blocks pipelined over pp and stage-resident caches."""
    from trlx_tpu.parallel.pipeline import pipeline_apply_cached

    kit = _pp_kit(config)
    if kit is None:
        raise NotImplementedError(
            f"pp is not available for {type(config).__name__}"
        )
    S = mesh.shape["pp"]
    L = num_layers_of(config)
    if L % S:
        raise ValueError(f"n_layer={L} must divide pp={S}")
    x = _embed(kit, config, backbone_params, input_ids, position_ids)
    T = input_ids.shape[1]
    C = cache["k"].shape[2]
    B = input_ids.shape[0]
    # explicit per-row biases (aux rides microbatch slicing, so batch-lead)
    pad = padding_bias(attention_mask)
    aux = {
        "global": jnp.broadcast_to(
            combine_biases(causal_bias(T, C, offset=cache_index), pad),
            (B, 1, T, C),
        )
    }
    if kit.windowed:
        aux["local"] = jnp.broadcast_to(
            _neo_local_bias(config, T, C, cache_index, pad), (B, 1, T, C)
        )
    if kit.takes_positions:
        aux["pos"] = position_ids

    if stacked is None:
        stacked = _stack_stages(
            [backbone_params[f"h_{i}"] for i in range(L)], S
        )
    flags = _local_flags(config, S) if kit.windowed else None
    block = kit.block_cls(config)

    def stage_fn(stage_params, h, aux_mb, stage_cache_mb, idx):
        # stage_cache_mb leaves [L/S, bm, C, ...]: scan layers, thread h
        params, lflags = stage_params if kit.windowed else (stage_params, None)
        body = _stage_body(
            kit, block, {**aux_mb, "idx": idx}, causal=False, cached=True
        )
        xs = (
            (params, stage_cache_mb, lflags)
            if kit.windowed
            else (params, stage_cache_mb)
        )
        h, new_kvs = jax.lax.scan(body, h, xs)
        return h, new_kvs

    stage_tree = (stacked, flags) if kit.windowed else stacked
    h, new_cache = pipeline_apply_cached(
        stage_fn, stage_tree, x, cache, cache_index, mesh,
        num_microbatches=num_microbatches, aux=aux,
    )
    return _ln_f(kit, config, backbone_params, h), new_cache


def make_pp_sampler_apply(
    config,
    mesh: Mesh,
    num_microbatches: int = 2,
):
    """Sampler ``apply_fn`` for a pp mesh: matches the contract of
    ``CausalLMWithValueHead`` applies in `trainer/ppo_trainer.py` —
    ``(params, input_ids, attention_mask, position_ids, cache,
    cache_index, last_only) -> {"logits", "values", "cache"}`` — with the
    trunk pipelined and the cache stage-resident. ``params`` is the PACKED
    tree from :func:`pp_stack_sampler_params` (blocks pre-stacked once per
    sampler invocation, not once per decoded token). Logits/values are
    computed at the LAST position only (shape [B, 1, ...]), which is all
    the sampler reads for both prefill and decode."""
    kit = _pp_kit(config)
    v_head = MLPHead(
        hidden_size_of(config), 1, dtype=config.dtype,
        param_dtype=config.param_dtype,
    )

    def apply_fn(params, input_ids, attention_mask=None, position_ids=None,
                 cache=None, cache_index=None, last_only=False):
        h, new_cache = pp_cached_hidden(
            config, params["transformer"], input_ids, attention_mask,
            position_ids, cache, cache_index, mesh, num_microbatches,
            stacked=params["stacked_blocks"],
        )
        hs = h[:, -1:]
        logits = _logits(kit, config, params["transformer"], hs)
        values = v_head.apply({"params": params["v_head"]}, hs)[..., 0]
        return {"logits": logits, "values": values, "cache": new_cache}

    return apply_fn


# ----------------------- pp seq2seq rollout decode ----------------------- #
#
# The T5 family's rollouts under a pp mesh (VERDICT r3 #3 — previously the
# compiled seq2seq sampler stayed GSPMD with params replicated over pp):
# - the ENCODER runs once per chunk through the same GPipe schedule as the
#   update's forward, with its blocks stage-stacked and resident;
# - the decoder self-attention KV cache is layer-major [L_dec, B, cap, H,
#   d_kv] sharded P(pp, batch) — each device holds its stage's cache only;
# - the cross-attention K/V are precomputed ONCE per chunk from the encoder
#   output (one batched einsum over the layer-stacked EncDecAttention
#   projections) into the same layer-major stage-resident layout, and ride
#   the schedule as pipeline_apply_cached's READ-ONLY ``static_cache``;
# - embeddings, rel-pos bias tables, final LayerNorms, LM head, and the
#   value head stay replicated over pp (small, need the full batch).
#
# Reference capability being scaled: the fork's T5 generate path
# (`ppo_models.py:620-622`), which on torch runs a full replicated model.


def pp_t5_init_cache(config, batch_size: int, capacity: int):
    """Layer-major decoder self-attn KV buffers for pp seq2seq decode
    (bf16 — the t5 cache ships bf16 only, matching `init_t5_cache`)."""
    shape = (
        config.num_decoder_layers, batch_size, capacity,
        config.num_heads, config.d_kv,
    )
    dtype = jnp.dtype(config.dtype)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def pp_t5_stack_sampler_params(config, mesh: Mesh, params):
    """Stack BOTH T5 stacks' blocks for the pp sampler, once per invocation
    (the seq2seq analogue of :func:`pp_stack_sampler_params`)."""
    from jax.sharding import NamedSharding, PartitionSpec

    S = mesh.shape["pp"]
    t5 = params["t5"]
    pin = lambda tree: jax.tree_util.tree_map(
        lambda p: jax.lax.with_sharding_constraint(
            p, NamedSharding(mesh, PartitionSpec("pp"))
        ),
        tree,
    )
    return {
        **params,
        "enc_stacked": pin(_stack_stages(
            [t5[f"enc_{i}"] for i in range(config.num_layers)], S
        )),
        "dec_stacked": pin(_stack_stages(
            [t5[f"dec_{i}"] for i in range(config.num_decoder_layers)], S
        )),
    }


def make_pp_seq2seq_sampler_fns(config, mesh: Mesh, num_microbatches: int = 2):
    """``(encode_fn, decode_fn, init_cross_kv_fn)`` for
    ``ops.sampling.make_seq2seq_sampler`` under a pp mesh. All three consume
    the PACKED param tree from :func:`pp_t5_stack_sampler_params`. Bias
    construction mirrors ``T5Model.encode`` / ``T5Model.decode`` exactly
    (token-exact parity vs the GSPMD sampler is pinned in
    ``tests/test_pp_integration.py``)."""
    from jax.sharding import NamedSharding, PartitionSpec

    from trlx_tpu.models.t5 import T5DecoderBlock, T5Model
    from trlx_tpu.ops.attention import NEG_INF
    from trlx_tpu.parallel.mesh import BATCH_AXES
    from trlx_tpu.parallel.pipeline import pipeline_apply_cached

    backbone = T5Model(config)
    dtype = jnp.dtype(config.dtype)
    v_head = MLPHead(
        config.d_model, 1, dtype=config.dtype, param_dtype=config.param_dtype
    )
    resident = NamedSharding(mesh, PartitionSpec("pp", BATCH_AXES))

    def bb(t5_params, fn, *args):
        return backbone.apply({"params": t5_params}, *args, method=fn)

    def encode_fn(packed, input_ids, attention_mask):
        return _pp_t5_encode(
            config, packed["t5"], input_ids, attention_mask, mesh,
            num_microbatches, enc_stacked=packed["enc_stacked"],
        )

    def init_cross_kv_fn(packed, encoder_hidden):
        # one batched einsum over the layer-stacked EncDecAttention k/v
        # projections (T5Attention.project_kv per layer, vectorized), cast
        # exactly as nn.Dense(dtype=cfg.dtype) would
        dec = packed["dec_stacked"]["EncDecAttention"]
        B, T_enc = encoder_hidden.shape[:2]
        L = config.num_decoder_layers
        layer_sh = NamedSharding(mesh, PartitionSpec("pp"))

        def proj(kernel):  # [S, L/S, d_model, inner] -> [L, B, T, H, d_kv]
            w = kernel.reshape(L, config.d_model, -1).astype(dtype)
            # keep the layer dim sharded over pp through the reshape so
            # GSPMD partitions the einsum per stage (each device projects
            # only its own L/S layers) instead of all-gathering the
            # kernels and computing all L layers replicated
            w = jax.lax.with_sharding_constraint(w, layer_sh)
            out = jnp.einsum("btd,ldi->lbti", encoder_hidden.astype(dtype), w)
            out = out.reshape(L, B, T_enc, config.num_heads, config.d_kv)
            return jax.lax.with_sharding_constraint(out, resident)

        return {"k": proj(dec["k"]["kernel"]), "v": proj(dec["v"]["kernel"])}

    def decode_fn(packed, decoder_input_ids, encoder_mask=None,
                  decoder_mask=None, cache=None, cache_index=None,
                  cross_kv=None):
        t5p = packed["t5"]
        B, T = decoder_input_ids.shape
        y = bb(t5p, lambda m, i: m.shared(i).astype(dtype), decoder_input_ids)
        C = cache["k"].shape[2]
        q_pos = cache_index + jnp.arange(T)
        k_pos = jnp.arange(C)
        causal = jnp.where(
            k_pos[None, :] <= q_pos[:, None], 0.0, NEG_INF
        )[None, None]
        self_bias = (
            bb(t5p, lambda m, q, k: m.dec_rel_bias(q, k), q_pos, k_pos)
            + causal
        )
        if decoder_mask is not None:
            self_bias = self_bias + jnp.where(
                decoder_mask[:, None, None, :] > 0, 0.0, NEG_INF
            )
        self_bias = jnp.broadcast_to(self_bias, (B,) + self_bias.shape[1:])
        cross_bias = jnp.where(
            encoder_mask[:, None, None, :] > 0, 0.0, NEG_INF
        ).astype(jnp.float32)
        dec_block = T5DecoderBlock(config)

        def stage_fn(stage_params, h, aux_mb, cache_mb, static_mb, idx):
            def body(h, xs):
                p, c_mb, x_mb = xs
                h, new_kv = dec_block.apply(
                    {"params": p}, h, aux_mb["sb"], aux_mb["cb"],
                    cache_kv=c_mb, cache_index=idx,
                    cross_kv=(x_mb["k"], x_mb["v"]),
                )
                return h, new_kv

            h, new_kvs = jax.lax.scan(
                body, h, (stage_params, cache_mb, static_mb)
            )
            return h, new_kvs

        h, new_cache = pipeline_apply_cached(
            stage_fn, packed["dec_stacked"], y, cache, cache_index, mesh,
            num_microbatches=num_microbatches,
            aux={"sb": self_bias, "cb": cross_bias}, static_cache=cross_kv,
        )
        h = bb(t5p, lambda m, v_: m.dec_final_ln(v_), h)
        logits = bb(t5p, T5Model.logits, h)
        values = v_head.apply({"params": packed["v_head"]}, h)[..., 0]
        return {"logits": logits, "values": values, "cache": new_cache}

    return encode_fn, decode_fn, init_cross_kv_fn
