"""Pipeline-parallel trunk forward for the GPT-2 family.

Integrates ``parallel/pipeline.py``'s GPipe primitive into the real model:
the full-sequence forwards the PPO update runs (policy ``response_forward``
and the frozen-ref scoring pass) route their transformer blocks through
``pipeline_apply`` over the mesh's ``pp`` axis, with embeddings and heads
running replicated over pp. This makes ``mesh: {dp: ..., pp: ...}`` a real
training capability rather than a standalone demo (the reference has no pp
at all — SURVEY §2.9 "PP: NO"; this is the beyond-parity axis).

Scope and composition:
- Stage s runs blocks ``[s*L/S, (s+1)*L/S)`` with an in-stage ``lax.scan``;
  activations hop stages via ``ppermute`` (GPipe schedule, differentiable).
- Param *residency* (at rest) follows the existing fsdp/tp partition
  rules. During the pipeline loop itself, stage params are all-gathered
  over fsdp at the shard_map boundary (`parallel/pipeline.py`): pp shards
  params/compute *across stages*; fsdp shards the at-rest copy and the
  optimizer state, not the running stage's working set.
- Autoregressive decode (round 3) runs the SAME pipeline schedule with
  stage-resident KV caches: the sampler's cache is layer-major
  ``[L, B, C, H, Dh]`` sharded over pp, so each device holds only its
  stage's layers and cache during rollouts (``pp_cached_hidden`` /
  ``make_pp_sampler_apply`` below) — no replicated full-model copy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from trlx_tpu.models.gpt2 import Block, GPT2Config, GPT2Model
from trlx_tpu.models.heads import MLPHead
from trlx_tpu.ops.attention import causal_dispatch
from trlx_tpu.parallel.pipeline import pipeline_apply, stack_stage_params


def supports_pp(model_config) -> bool:
    return isinstance(model_config, GPT2Config)


def _stack_stages(block_params, stages: int):
    """[L] per-block param trees -> leaves [S, L/S, ...] (stage-major)."""
    per = len(block_params) // stages
    stage_trees = [
        jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs, axis=0),
            *block_params[s * per : (s + 1) * per],
        )
        for s in range(stages)
    ]
    return stack_stage_params(stage_trees)


def pp_hidden_forward(
    config: GPT2Config,
    backbone_params,
    input_ids: jax.Array,  # [B, T]
    attention_mask: jax.Array,  # [B, T]
    mesh: Mesh,
    num_microbatches: int = 2,
) -> jax.Array:
    """Full-sequence causal trunk forward (embed -> pp blocks -> ln_f),
    numerically identical to ``GPT2Model.__call__`` with ``cache=None``.
    Embedding / ln_f / heads reuse the flax module methods (one definition)
    — only the block loop is replaced by the pipeline schedule."""
    S = mesh.shape["pp"]
    if config.n_layer % S:
        raise ValueError(
            f"n_layer={config.n_layer} must divide into pp={S} stages"
        )
    backbone = GPT2Model(config)
    position_ids = jnp.clip(jnp.cumsum(attention_mask, axis=-1) - 1, 0, None)
    x = backbone.apply(
        {"params": backbone_params}, input_ids, position_ids,
        method=GPT2Model.embed,
    )
    bias, causal = causal_dispatch(
        input_ids.shape[1], None, None, attention_mask
    )

    stacked = _stack_stages(
        [backbone_params[f"h_{i}"] for i in range(config.n_layer)], S
    )
    block = Block(config)

    def stage_fn(stage_params, h, bias_mb):
        def body(h, p):
            h, _ = block.apply({"params": p}, h, bias_mb, causal=causal)
            return h, None

        h, _ = jax.lax.scan(body, h, stage_params)
        return h

    h = pipeline_apply(
        stage_fn, stacked, x, mesh,
        num_microbatches=num_microbatches, aux=bias,
    )
    return backbone.apply(
        {"params": backbone_params}, h, method=lambda m, v: m.ln_f(v)
    )


def _logits(config: GPT2Config, backbone_params, hidden: jax.Array):
    """Tied LM head on (already-sliced) hidden states via the module's own
    definition (``GPT2Model.logits``)."""
    return GPT2Model(config).apply(
        {"params": backbone_params}, hidden, method=GPT2Model.logits
    )


def pp_response_forward(
    config: GPT2Config,
    params,  # CausalLMWithValueHead params: {"transformer", "v_head"}
    input_ids: jax.Array,
    attention_mask: jax.Array,
    query_length: int,
    mesh: Mesh,
    num_microbatches: int = 2,
):
    """pp counterpart of ``CausalLMWithValueHead.response_forward``:
    (logits, values) over the response-predicting positions Q-1..Q+R-2."""
    h = pp_hidden_forward(
        config, params["transformer"], input_ids, attention_mask,
        mesh, num_microbatches,
    )
    hs = h[:, query_length - 1 : -1]
    v_head = MLPHead(
        config.n_embd, 1, dtype=config.dtype, param_dtype=config.param_dtype
    )
    values = v_head.apply({"params": params["v_head"]}, hs)[..., 0]
    return _logits(config, params["transformer"], hs), values


def pp_ref_logits(
    config: GPT2Config,
    backbone_params,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    query_length: int,
    mesh: Mesh,
    num_microbatches: int = 2,
) -> jax.Array:
    """Frozen-reference logits over response-predicting positions (the
    full-copy ref path; hydra's shared-trunk branch is not offered under
    pp — the trunk capture point sits mid-pipeline)."""
    h = pp_hidden_forward(
        config, backbone_params, input_ids, attention_mask,
        mesh, num_microbatches,
    )
    return _logits(config, backbone_params, h[:, query_length - 1 : -1])


# --------------------------- pp rollout decode --------------------------- #
#
# Round 3: decode under a pp mesh no longer replicates the full model per
# device. The sampler's KV cache becomes layer-major [L, B, C, H, Dh]
# sharded P(pp, (dp, fsdp)) — each device holds the cache AND compute of
# its own stage's L/S layers only — and every sampler forward (prefill +
# each decode token) runs the GPipe schedule with the cache resident in
# the stages (`parallel/pipeline.py::pipeline_apply_cached`). Embedding,
# ln_f, LM head, and the value head stay replicated over pp (they are a
# small fraction of weights and need the full batch anyway).


def pp_init_cache(config: GPT2Config, batch_size: int, capacity: int):
    """Layer-major KV buffers for pp decode: ``{"k","v"}: [L, B, C, H, Dh]``
    (vs the GSPMD sampler's per-layer tuple). ``kv_cache_dtype="int8"``
    composes: value+scale leaves, stage-sliced and microbatch-sliced like
    any other cache leaf (`write_cache` keys on the ``k_scale`` entry, so
    the per-layer dict the stage scan hands to ``Block`` is already in the
    quantized layout)."""
    head_dim = config.n_embd // config.n_head
    shape = (config.n_layer, batch_size, capacity, config.n_head, head_dim)
    kv_dtype = getattr(config, "kv_cache_dtype", "bfloat16")
    if kv_dtype == "int8":
        sshape = shape[:-1] + (1,)
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(sshape, jnp.bfloat16),
            "v_scale": jnp.zeros(sshape, jnp.bfloat16),
        }
    if kv_dtype != "bfloat16":
        # mirror kv_buffers: a future cache dtype (e.g. fp8) must fail loudly
        # here rather than silently allocating bf16 stage buffers
        raise ValueError(
            f"kv_cache_dtype={kv_dtype!r} has no pp stage-resident layout yet"
        )
    dtype = jnp.dtype(config.dtype)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def pp_stack_sampler_params(config: GPT2Config, mesh: Mesh, params):
    """Pre-stack the trunk blocks for the pp sampler, ONCE per sampler
    invocation (outside the decode scan): the jnp.stack of every layer and
    the regather to P('pp') residency are loop-invariant, and leaving them
    inside the per-token apply would rely on XLA hoisting them out of the
    while-loop body (round-3 review). Returns the packed params pytree the
    ``make_pp_sampler_apply`` closure expects."""
    from jax.sharding import NamedSharding, PartitionSpec

    S = mesh.shape["pp"]
    stacked = _stack_stages(
        [params["transformer"][f"h_{i}"] for i in range(config.n_layer)], S
    )
    stacked = jax.tree_util.tree_map(
        lambda p: jax.lax.with_sharding_constraint(
            p, NamedSharding(mesh, PartitionSpec("pp"))
        ),
        stacked,
    )
    return {
        "transformer": params["transformer"],
        "v_head": params["v_head"],
        "stacked_blocks": stacked,
    }


def pp_cached_hidden(
    config: GPT2Config,
    backbone_params,
    input_ids: jax.Array,  # [B, T]
    attention_mask: jax.Array,  # [B, C] cache-validity mask
    position_ids: jax.Array,  # [B, T]
    cache,  # pp_init_cache layout
    cache_index,
    mesh: Mesh,
    num_microbatches: int = 2,
    stacked=None,  # pre-stacked blocks (pp_stack_sampler_params)
):
    """(hidden after ln_f, new cache) for a cached forward (prefill T=Q or
    decode T=1) with blocks pipelined over pp and stage-resident caches."""
    from trlx_tpu.ops.attention import causal_bias, combine_biases, padding_bias
    from trlx_tpu.parallel.pipeline import pipeline_apply_cached

    S = mesh.shape["pp"]
    if config.n_layer % S:
        raise ValueError(f"n_layer={config.n_layer} must divide pp={S}")
    backbone = GPT2Model(config)
    x = backbone.apply(
        {"params": backbone_params}, input_ids, position_ids,
        method=GPT2Model.embed,
    )
    T = input_ids.shape[1]
    C = cache["k"].shape[2]
    B = input_ids.shape[0]
    # explicit per-row bias (aux rides microbatch slicing, so batch-lead it)
    bias = combine_biases(
        causal_bias(T, C, offset=cache_index), padding_bias(attention_mask)
    )
    bias = jnp.broadcast_to(bias, (B,) + bias.shape[1:])

    if stacked is None:
        stacked = _stack_stages(
            [backbone_params[f"h_{i}"] for i in range(config.n_layer)], S
        )
    block = Block(config)

    def stage_fn(stage_params, h, bias_mb, stage_cache_mb, idx):
        # stage_cache_mb leaves [L/S, bm, C, H, Dh]: scan layers, thread h
        def body(h, xs):
            p, kv = xs
            h, new_kv = block.apply(
                {"params": p}, h, bias_mb, cache_kv=kv, cache_index=idx,
                causal=False,
            )
            return h, new_kv

        h, new_kvs = jax.lax.scan(body, h, (stage_params, stage_cache_mb))
        return h, new_kvs

    h, new_cache = pipeline_apply_cached(
        stage_fn, stacked, x, cache, cache_index, mesh,
        num_microbatches=num_microbatches, aux=bias,
    )
    h = backbone.apply(
        {"params": backbone_params}, h, method=lambda m, v: m.ln_f(v)
    )
    return h, new_cache


def make_pp_sampler_apply(
    config: GPT2Config,
    mesh: Mesh,
    num_microbatches: int = 2,
):
    """Sampler ``apply_fn`` for a pp mesh: matches the contract of
    ``CausalLMWithValueHead`` applies in `trainer/ppo_trainer.py` —
    ``(params, input_ids, attention_mask, position_ids, cache,
    cache_index, last_only) -> {"logits", "values", "cache"}`` — with the
    trunk pipelined and the cache stage-resident. ``params`` is the PACKED
    tree from :func:`pp_stack_sampler_params` (blocks pre-stacked once per
    sampler invocation, not once per decoded token). Logits/values are
    computed at the LAST position only (shape [B, 1, ...]), which is all
    the sampler reads for both prefill and decode."""
    from trlx_tpu.models.heads import MLPHead

    v_head = MLPHead(
        config.n_embd, 1, dtype=config.dtype, param_dtype=config.param_dtype
    )

    def apply_fn(params, input_ids, attention_mask=None, position_ids=None,
                 cache=None, cache_index=None, last_only=False):
        h, new_cache = pp_cached_hidden(
            config, params["transformer"], input_ids, attention_mask,
            position_ids, cache, cache_index, mesh, num_microbatches,
            stacked=params["stacked_blocks"],
        )
        hs = h[:, -1:]
        logits = _logits(config, params["transformer"], hs)
        values = v_head.apply({"params": params["v_head"]}, hs)[..., 0]
        return {"logits": logits, "values": values, "cache": new_cache}

    return apply_fn
