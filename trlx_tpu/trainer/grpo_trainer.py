"""GRPO: group-relative PPO without a value function (beyond parity).

The reference ships classic PPO only; this adds the grouped-baseline
variant modern RLHF stacks favor for its memory profile — no value head
training, no GAE. Per prompt, ``group_size`` rollouts are sampled (the
orchestrator repeats each chunk prompt G times, contiguously —
`orchestrator/ppo_orchestrator.py::_expand_groups`); each rollout's
KL-shaped return is normalized against its own group:

    A_i = (R_i − mean_group) / (std_group + 1e-6)

broadcast over the response tokens, and optimized with the same clipped
surrogate (``vf_coef`` defaults to 0 so the value head, while still
present in the model, receives no training signal). Group advantages are
computed at experience time and stored in the rollout buffer's rewards
slot, so minibatch shuffling can never split a group.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from trlx_tpu.data.method_configs import register_method
from trlx_tpu.data.ppo_types import PPORolloutBatch
from trlx_tpu.ops.ppo_math import PPOConfig
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.ppo_trainer import PPOTrainer
from trlx_tpu.trainer.seq2seq_ppo_trainer import Seq2SeqPPOTrainer


@register_method
@dataclass
class GRPOConfig(PPOConfig):
    """PPO hyperparameters + the group size; GAE (gamma/lam) and the value
    loss are unused — ``vf_coef`` defaults to 0."""

    name: str = "GRPOConfig"
    group_size: int = 8
    vf_coef: float = 0.0


class GRPOMixin:
    """The GRPO behavior as a mixin over any PPO-family trainer: grouped
    chunk sampling (via ``self.group_size``), group-normalized advantages
    stored at experience time, and no value-function training."""

    def __init__(self, config, **kw):
        method: GRPOConfig = config.method
        if method.group_size < 2:
            raise ValueError(
                f"GRPO needs group_size >= 2 (got {method.group_size}): a "
                "single-rollout group has zero-variance baseline"
            )
        if method.vf_coef:
            raise ValueError(
                f"GRPO has no value function (vf_coef={method.vf_coef}); "
                "the returns slot carries a placeholder, so a nonzero "
                "vf_coef would regress values onto stale rollout values"
            )
        super().__init__(config, **kw)  # sets self.group_size (read by the
        # orchestrator to repeat prompts within each chunk)
        # run-health: skip the value-explained-variance stat — GRPO's
        # returns slot carries a placeholder (the stored rollout values),
        # so EV would read as a perfect-fit ~0-residual artifact and
        # mislead triage; the reward_* health quantiles stay on and
        # describe the group-whitened advantage distribution the updates
        # actually consume
        self._health_ev = False

    def _shape_rewards(self, logprobs, ref_logprobs, response_mask, scores, kl_coef):
        """Store group-normalized per-sequence advantages (broadcast over
        response tokens) in the rewards slot; rows arrive group-contiguous
        from the orchestrator's expansion."""
        rewards, mean_kl = super()._shape_rewards(
            logprobs, ref_logprobs, response_mask, scores, kl_coef
        )
        from trlx_tpu.ops.ppo_math import group_whiten

        returns = jnp.sum(rewards, axis=1)  # KL-regularized return R_i
        adv = group_whiten(returns, self.group_size)
        maskf = response_mask.astype(jnp.float32)
        return adv[:, None] * maskf, mean_kl

    def _advantages_and_returns(self, mb: PPORolloutBatch):
        """No GAE: mb.rewards already holds the group-normalized advantage
        per token. Returns are set to the stored values so the value loss
        starts at zero and stays zero-WEIGHTED (vf_coef=0); the logged
        vf_loss stat drifts nonzero as shared-backbone updates move the
        (untrained) value head — that is expected, not a grouping bug."""
        return mb.rewards, mb.values


@register_trainer
class GRPOTrainer(GRPOMixin, PPOTrainer):
    """GRPO over the causal PPO path."""


@register_trainer
class Seq2SeqGRPOTrainer(GRPOMixin, Seq2SeqPPOTrainer):
    """GRPO over the fork's T5/UL2 seq2seq path (decoder rollouts grouped
    per encoder prompt)."""
