"""Shared trainer machinery: train state, optimizer, layer freezing.

Replaces the reference's AdamW + cosine schedule setup
(``accelerate_base_model.py:94-106``) and ``num_layers_unfrozen`` freezing
(``ilql_models.py:217-225``). Freezing is an optax mask (frozen params get
zero updates) — under GSPMD the frozen leaves still shard, they just never
change, which is the TPU analogue of requires_grad=False.
"""

from __future__ import annotations

import re
from typing import Any, NamedTuple, Optional, Tuple

import flax.struct as struct
import jax
import jax.numpy as jnp
import optax

from trlx_tpu.data.configs import TrainConfig


@struct.dataclass
class TrainState:
    """Minimal explicit train state; RNG and KL-controller state are threaded
    by the host loop (they are host-decision values, not gradient state)."""

    params: Any
    opt_state: Any
    step: jax.Array  # int32 scalar


def unfrozen_param_mask(
    params: Any,
    num_layers_unfrozen: int,
    n_layer: int,
    zero_freezes_all: bool = False,
) -> Any:
    """True for trainable leaves. With ``num_layers_unfrozen=k > 0``, only the
    top-k transformer blocks + final layernorm + heads train.

    What the reference actually does with ``num_layers_unfrozen`` differs by
    path, and the two are mapped here via ``zero_freezes_all``:

    - **PPO path** (``zero_freezes_all=False``): the freezing block in
      ``accelerate_base_model.py:55-69`` is **commented out** in the
      reference as shipped — the policy trains ALL layers regardless of the
      setting (it only sizes the hydra KL-ref branch, ``ppo_models.py:
      525-536``). So ``k <= 0`` trains everything here, and ``k > 0`` is
      the re-enabled behavior of that commented code (freeze the bottom
      ``n_layer - k`` blocks), offered as real work-avoidance.
    - **ILQL path** (``zero_freezes_all=True``): ``ilql_models.py:217-225``
      is live code — ``0`` freezes ALL blocks, ``k > 0`` freezes the bottom
      ``n_layer - k``, negative freezes none. ``k == 0`` therefore maps to
      ``first_trainable == n_layer`` (every block frozen; heads + ln_f
      still train).

    Documented divergence (PARITY.md quirks): the reference freezes only
    the *blocks* — wte/wpe stay trainable; this mask also freezes the
    embeddings below the branch point, consistent with the hydra branch
    point being the first trainable position."""
    if num_layers_unfrozen > n_layer:
        raise ValueError(
            f"model.num_layers_unfrozen={num_layers_unfrozen} exceeds "
            f"n_layer={n_layer}"
        )
    if num_layers_unfrozen < 0 or (
        num_layers_unfrozen == 0 and not zero_freezes_all
    ):
        return jax.tree_util.tree_map(lambda _: True, params)
    first_trainable = n_layer - num_layers_unfrozen

    def mask_for(path, leaf):
        name = "/".join(
            str(getattr(p, "key", getattr(p, "idx", p))) for p in path
        )
        m = re.search(r"h_(\d+)/", name)
        if m:
            return int(m.group(1)) >= first_trainable
        if "wte" in name or "wpe" in name or "encoder" in name:
            return False
        return True  # ln_f, value/Q heads, anything else

    return jax.tree_util.tree_map_with_path(mask_for, params)


def stochastic_round(x32: jax.Array, key: jax.Array, dtype) -> jax.Array:
    """f32 -> ``dtype`` with stochastic rounding (unbiased: E[out] == x).

    Adds uniform noise below the kept mantissa bits of the IEEE-754 pattern
    and truncates — the standard trick for accumulating EMAs whose per-step
    increment ((1-b2)·g² with b2 up to 0.999) sits below bf16's 2^-8
    relative resolution; round-to-nearest would systematically drop it and
    the moment would stall at its old value."""
    dtype = jnp.dtype(dtype)
    if dtype == jnp.float32:
        return x32
    if dtype != jnp.bfloat16:
        raise ValueError(f"stochastic_round supports bfloat16, got {dtype}")
    bits = jax.lax.bitcast_convert_type(x32.astype(jnp.float32), jnp.uint32)
    noise = jax.random.bits(key, bits.shape, jnp.uint32) & jnp.uint32(0xFFFF)
    rounded = jax.lax.bitcast_convert_type(
        (bits + noise) & jnp.uint32(0xFFFF0000), jnp.float32
    ).astype(jnp.bfloat16)
    # adding noise to an inf/nan bit pattern would walk into nan space
    return jnp.where(jnp.isfinite(x32), rounded, x32.astype(jnp.bfloat16))


class ScaleByAdamLPState(NamedTuple):
    """Adam state with moments stored in a reduced dtype (mu/nu trees mirror
    the param tree, so partition rules shard them like ScaleByAdamState's)."""

    count: jax.Array
    mu: Any
    nu: Any


def scale_by_adam_low_precision(
    b1: float, b2: float, eps: float, moment_dtype
) -> optax.GradientTransformation:
    """``optax.scale_by_adam`` with BOTH moments stored in ``moment_dtype``
    (optax only offers ``mu_dtype``). All update math runs in f32; stores go
    through :func:`stochastic_round`, keyed deterministically per
    (step, leaf) — bitwise reproducible, no RNG state to checkpoint.

    Halves the optimizer's per-step HBM traffic (m+v read+write is ~8B/param
    at f32 — measured ~24% of the bench train step) and its resident bytes
    (the `test_neox20b_sharding.py` budget for the 20B stretch)."""
    moment_dtype = jnp.dtype(moment_dtype)

    def init_fn(params):
        zeros = lambda t: jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, moment_dtype), t
        )
        return ScaleByAdamLPState(
            count=jnp.zeros((), jnp.int32), mu=zeros(params), nu=zeros(params)
        )

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        f32 = lambda t: jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32), t
        )
        mu32 = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1.0 - b1) * g, f32(state.mu), f32(updates)
        )
        nu32 = jax.tree_util.tree_map(
            lambda v, g: b2 * v + (1.0 - b2) * g * g, f32(state.nu), f32(updates)
        )
        c1 = 1.0 - b1 ** count.astype(jnp.float32)
        c2 = 1.0 - b2 ** count.astype(jnp.float32)
        new_updates = jax.tree_util.tree_map(
            lambda m, v: (m / c1) / (jnp.sqrt(v / c2) + eps), mu32, nu32
        )
        # rbg keys: XLA's RngBitGenerator is ~3x cheaper than threefry for
        # the 2N uint32 draws a full-model SR store needs — with threefry
        # the RNG cost exceeded the halved-moment traffic saving (measured
        # +120ms vs -40ms per 32-step phase at the bench shape)
        # the literal seed is the CONTRACT here: stochastic rounding must
        # be bitwise reproducible per (step, leaf) with no RNG state to
        # checkpoint — it is noise injection, not statistical sampling
        base = jax.random.fold_in(jax.random.key(0x5EED, impl="rbg"), count)  # tpu-lint: disable=fixed-seed
        leaves_mu, treedef = jax.tree_util.tree_flatten(mu32)
        leaves_nu = treedef.flatten_up_to(nu32)
        keys = jax.random.split(base, 2 * len(leaves_mu))
        mu_st = treedef.unflatten(
            [
                stochastic_round(x, keys[i], moment_dtype)
                for i, x in enumerate(leaves_mu)
            ]
        )
        nu_st = treedef.unflatten(
            [
                stochastic_round(x, keys[len(leaves_mu) + i], moment_dtype)
                for i, x in enumerate(leaves_nu)
            ]
        )
        return new_updates, ScaleByAdamLPState(count=count, mu=mu_st, nu=nu_st)

    return optax.GradientTransformation(init_fn, update_fn)


def stop_frozen_gradients(params: Any, trainable_mask: Optional[Any]) -> Any:
    """``stop_gradient`` on every frozen param leaf, for use *inside* a
    ``loss_fn`` before the forward. The gradients of frozen leaves become
    structural zeros, so XLA dead-code-eliminates their entire backward —
    with bottom-layer freezing that prunes the backprop below the branch
    point (the reference gets the same pruning from requires_grad=False).
    Also makes clip_by_global_norm see only trainable gradients, matching
    torch's behavior where frozen params simply have no .grad."""
    if trainable_mask is None or all(jax.tree_util.tree_leaves(trainable_mask)):
        return params
    return jax.tree_util.tree_map(
        lambda p, t: p if t else jax.lax.stop_gradient(p), params, trainable_mask
    )


def make_optimizer(
    train_config: TrainConfig,
    total_steps: int,
    trainable_mask: Optional[Any] = None,
) -> optax.GradientTransformation:
    """grad-clip -> AdamW(cosine lr_init->lr_target) [-> freeze mask].

    Reference: AdamW + CosineAnnealingLR from lr_init to lr_target
    (`accelerate_base_model.py:94-106`). With
    ``train.adam_moment_dtype: "bfloat16"`` the Adam moments are stored in
    bf16 with stochastic rounding (same chain order as ``optax.adamw``:
    scale_by_adam -> add_decayed_weights -> scale_by_learning_rate)."""
    schedule = optax.cosine_decay_schedule(
        init_value=train_config.lr_init,
        decay_steps=max(total_steps, 1),
        alpha=train_config.lr_target / train_config.lr_init
        if train_config.lr_init
        else 1.0,
    )
    if train_config.adam_moment_dtype not in ("float32", "bfloat16"):
        # validate the raw string BEFORE jnp.dtype — an unknown name (e.g.
        # the natural typo "bf16") would otherwise die in numpy's opaque
        # TypeError instead of this message
        raise ValueError(
            f"train.adam_moment_dtype must be float32 or bfloat16, got "
            f"{train_config.adam_moment_dtype!r}"
        )
    moment_dtype = jnp.dtype(train_config.adam_moment_dtype)
    if moment_dtype == jnp.float32:
        adam = optax.adamw(
            learning_rate=schedule,
            b1=train_config.opt_betas[0],
            b2=train_config.opt_betas[1],
            eps=train_config.opt_eps,
            weight_decay=train_config.weight_decay,
        )
    else:
        adam = optax.chain(
            scale_by_adam_low_precision(
                b1=train_config.opt_betas[0],
                b2=train_config.opt_betas[1],
                eps=train_config.opt_eps,
                moment_dtype=moment_dtype,
            ),
            optax.add_decayed_weights(train_config.weight_decay),
            optax.scale_by_learning_rate(schedule),
        )
    if trainable_mask is not None and not all(
        jax.tree_util.tree_leaves(trainable_mask)
    ):
        # Frozen leaves carry NO optimizer state and see no Adam traffic
        # (optax.masked skips them entirely) — with bottom-layer freezing
        # the moments shrink to the trainable slice, exactly as torch's
        # requires_grad=False does for the reference. The trailing
        # set_to_zero is a hard guarantee that frozen params never move
        # even if a caller feeds unstopped gradients. (Checkpoints from
        # the earlier full-size-moment masked layout do not restore into
        # this structure — frozen-mask runs must restart.)
        tx = optax.chain(
            optax.clip_by_global_norm(train_config.grad_clip),
            optax.masked(adam, trainable_mask),
            optax.masked(
                optax.set_to_zero(),
                jax.tree_util.tree_map(lambda t: not t, trainable_mask),
            ),
        )
    elif trainable_mask is not None:
        # all-trainable: keep the historical opt-state pytree structure
        # (chain(chain(clip, adam), masked(set_to_zero, all-False))) so
        # pre-existing Orbax checkpoints of default-config runs still
        # restore leaf-for-leaf
        tx = optax.chain(
            optax.chain(
                optax.clip_by_global_norm(train_config.grad_clip), adam
            ),
            optax.masked(
                optax.set_to_zero(),
                jax.tree_util.tree_map(lambda t: not t, trainable_mask),
            ),
        )
    else:
        tx = optax.chain(
            optax.clip_by_global_norm(train_config.grad_clip),
            adam,
        )
    return tx
