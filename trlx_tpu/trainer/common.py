"""Shared trainer machinery: train state, optimizer, layer freezing.

Replaces the reference's AdamW + cosine schedule setup
(``accelerate_base_model.py:94-106``) and ``num_layers_unfrozen`` freezing
(``ilql_models.py:217-225``). Freezing is an optax mask (frozen params get
zero updates) — under GSPMD the frozen leaves still shard, they just never
change, which is the TPU analogue of requires_grad=False.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Tuple

import flax.struct as struct
import jax
import jax.numpy as jnp
import optax

from trlx_tpu.data.configs import TrainConfig


@struct.dataclass
class TrainState:
    """Minimal explicit train state; RNG and KL-controller state are threaded
    by the host loop (they are host-decision values, not gradient state)."""

    params: Any
    opt_state: Any
    step: jax.Array  # int32 scalar


def unfrozen_param_mask(params: Any, num_layers_unfrozen: int, n_layer: int) -> Any:
    """True for trainable leaves. With ``num_layers_unfrozen=k > 0``, only the
    top-k transformer blocks + final layernorm + heads train (reference
    freezes everything below the branch point)."""
    if num_layers_unfrozen < 0:
        return jax.tree_util.tree_map(lambda _: True, params)
    first_trainable = n_layer - num_layers_unfrozen

    def mask_for(path, leaf):
        name = "/".join(
            str(getattr(p, "key", getattr(p, "idx", p))) for p in path
        )
        m = re.search(r"h_(\d+)/", name)
        if m:
            return int(m.group(1)) >= first_trainable
        if "wte" in name or "wpe" in name or "encoder" in name:
            return False
        return True  # ln_f, value/Q heads, anything else

    return jax.tree_util.tree_map_with_path(mask_for, params)


def make_optimizer(
    train_config: TrainConfig,
    total_steps: int,
    trainable_mask: Optional[Any] = None,
) -> optax.GradientTransformation:
    """grad-clip -> AdamW(cosine lr_init->lr_target) [-> freeze mask].

    Reference: AdamW + CosineAnnealingLR from lr_init to lr_target
    (`accelerate_base_model.py:94-106`).
    """
    schedule = optax.cosine_decay_schedule(
        init_value=train_config.lr_init,
        decay_steps=max(total_steps, 1),
        alpha=train_config.lr_target / train_config.lr_init
        if train_config.lr_init
        else 1.0,
    )
    tx = optax.chain(
        optax.clip_by_global_norm(train_config.grad_clip),
        optax.adamw(
            learning_rate=schedule,
            b1=train_config.opt_betas[0],
            b2=train_config.opt_betas[1],
            eps=train_config.opt_eps,
            weight_decay=train_config.weight_decay,
        ),
    )
    if trainable_mask is not None:
        tx = optax.chain(
            tx,
            optax.masked(
                optax.set_to_zero(),
                jax.tree_util.tree_map(lambda t: not t, trainable_mask),
            ),
        )
    return tx
