"""Seq2seq (T5/UL2) PPO trainer — the fork's headline path.

Re-design of the fork's T5 wiring inside ``AcceleratePPOModel``:
``shift_tokens_right`` + ``get_model_inputs`` (`accelerate_ppo_model.py
:18-25,63-76`), the T5 generate kwargs with decoder-start / forced Chinese
BOS (`accelerate_ppo_model.py:50-54`, `ppo_models.py:620-622`), and the
T5 value-head forward (`ppo_models.py:624-655`).

The rollout layout maps cleanly onto the shared PPO machinery: the "query"
is the encoder input, the "response" the decoder output; logprobs/values
align position-for-position with the teacher-forced forward on
``shift_right(response)`` (verified in ``tests/test_t5_parity.py``).
"""

from __future__ import annotations

import functools
from typing import Dict

import jax.numpy as jnp

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.data.ppo_types import PPORolloutBatch
from trlx_tpu.models.heads import T5WithValueHead
from trlx_tpu.models.t5 import (
    T5Config,
    T5Model,
    T5_PARTITION_RULES,
    init_t5_cache,
    shift_tokens_right,
)
from trlx_tpu.ops.sampling import make_seq2seq_sampler
from trlx_tpu.parallel import logprobs_from_logits
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.ppo_trainer import PPOTrainer, _policy_entropy


def get_t5_arch(config: TRLConfig):
    model_cfg = config.model
    overrides = dict(model_cfg.model_arch)
    overrides.setdefault("dtype", config.train.dtype)
    overrides.setdefault("param_dtype", config.train.param_dtype)
    if model_cfg.model_path:
        from trlx_tpu.models.conversion import load_t5_checkpoint

        arch, params = load_t5_checkpoint(
            model_cfg.model_path, dtype=config.train.param_dtype
        )
        arch = T5Config(
            **{
                **arch.__dict__,
                "dtype": overrides["dtype"],
                "param_dtype": overrides["param_dtype"],
            }
        )
        return arch, params
    return T5Config.from_dict(overrides), None


@register_trainer("Seq2SeqPPOTrainer")
@register_trainer("T5PPOTrainer")
class Seq2SeqPPOTrainer(PPOTrainer):
    backbone_key = "t5"

    def _supports_rollout_cast(self) -> bool:
        # T5 consumes f32 params directly (RMSNorm scales multiply the
        # f32-normalized activation; RelPosBias feeds attention at f32), so
        # a compute-dtype copy would not be bit-identical — keep masters
        return False

    def _supports_logprob_chunk(self) -> bool:
        # this trainer overrides _forward_logprobs_values with its own
        # (encoder+decoder) forward; the chunked causal path never runs,
        # so the flag refuses at construction instead of no-opping
        return False

    def _supports_continuous_engine(self) -> bool:
        # the continuous engine drives the causal apply/cache contract;
        # the seq2seq sampler splits encode/decode with cross-KV — a
        # rollout.engine: continuous config refuses at construction
        # instead of silently running the fixed path
        return False

    def _validate_pp_mesh(self, config, train) -> None:
        # pp for seq2seq: BOTH trunk stacks pipeline in the update's
        # forwards (`pp_runner.pp_t5_forward`), and (round 4) the rollout
        # sampler is stage-resident too — pipelined encoder, layer-major
        # decoder KV cache sharded P(pp, batch), cross-attention K/V
        # precomputed per chunk into the same resident layout
        # (`make_pp_seq2seq_sampler_fns`)
        from trlx_tpu.models.pp_runner import supports_pp_seq2seq

        if not supports_pp_seq2seq(self.model_config):
            raise NotImplementedError(
                f"seq2seq pp is integrated for the T5 family, not "
                f"{type(self.model_config).__name__}"
            )
        L_enc = self.model_config.num_layers
        L_dec = self.model_config.num_decoder_layers
        v = train.pp_virtual_stages
        # interleaved schedule (round 4): both stacks accept v > 1 — each
        # device holds v round-robin layer chunks per stack; the train
        # forwards pay two schedules, so the ~v× bubble shrink applies
        # twice. Decode keeps v=1 (contiguous stage-resident caches).
        if L_enc % (self.pp_stages * v) or L_dec % (self.pp_stages * v):
            raise ValueError(
                f"num_layers={L_enc} and num_decoder_layers={L_dec} must "
                f"both divide into pp={self.pp_stages} stages x "
                f"{v} virtual"
            )

    def _check_response_budget(self, train) -> None:
        # For seq2seq, gen max_length caps *decoder* tokens (incl. the
        # start token), independent of the encoder budget train.seq_length;
        # >= 2 guarantees at least one real response token per rollout.
        if 0 < self.gen_config.max_length < 2:
            raise ValueError(
                f"gen_kwargs max_length={self.gen_config.max_length} counts "
                "decoder tokens incl. the start token; it must be >= 2 so "
                "every rollout has at least one response token (a zero-"
                "length response's terminal reward is silently dropped)"
            )

    def bind_prompt_budget(self, pipeline, role: str = "train") -> None:
        # encoder prompt lengths don't consume the decoder's max_length
        # budget, so there is nothing to validate or shrink here
        pass

    def _setup_model(self):
        from trlx_tpu.models.registry import get_model_family

        if self.config.model.num_layers_unfrozen > 0:
            # The reference never freezes T5 (its PPO freezing block is
            # commented out and operates on a causal `transformer.h`
            # stack, `accelerate_base_model.py:55-69`); our mask keys on
            # causal block names (`h_<i>`), so a positive value here would
            # silently train the FULL model while claiming to freeze —
            # refuse instead.
            raise NotImplementedError(
                "num_layers_unfrozen > 0 is not defined for the seq2seq "
                "(encoder-decoder) family — the reference trains the full "
                "T5 and uses a full frozen copy as the KL reference "
                "(`ppo_orchestrator.py:41-43`); set num_layers_unfrozen "
                "to 0 or -1"
            )
        self.family = get_model_family("t5")
        self.model_config, init_params = get_t5_arch(self.config)
        self.model = T5WithValueHead(self.model_config)
        self.backbone = T5Model(self.model_config)
        self.partition_rules = T5_PARTITION_RULES
        return init_params

    def _amend_gen_kwargs(self, gen_kwargs: Dict) -> None:
        gen_kwargs.setdefault(
            "decoder_start_token_id", self.model_config.decoder_start_token_id
        )

    def _n_layers(self) -> int:
        return self.model_config.num_decoder_layers

    def _init_params(self, rng):
        return self.model.init(
            rng,
            jnp.zeros((1, 8), jnp.int32),
            decoder_input_ids=jnp.zeros((1, 2), jnp.int32),
        )["params"]

    def _make_sampler(self):
        if self.pp_stages > 1:
            from trlx_tpu.models.pp_runner import (
                make_pp_seq2seq_sampler_fns,
                pp_t5_init_cache,
                pp_t5_stack_sampler_params,
            )

            enc_fn, dec_fn, xkv_fn = make_pp_seq2seq_sampler_fns(
                self.model_config, self.mesh, self.pp_microbatches
            )
            inner = make_seq2seq_sampler(
                enc_fn,
                dec_fn,
                xkv_fn,
                functools.partial(pp_t5_init_cache, self.model_config),
                self.gen_config,
                with_values=True,
                # residency constraints live inside the pp fns (the
                # schedule's shard_map out_specs re-pin every step)
                cache_sharding=None,
            )

            def sampler(params, prompt_ids, prompt_mask, rng):
                # stack both stacks' blocks ONCE per invocation, not per
                # decoded token inside the sampler's scan
                packed = pp_t5_stack_sampler_params(
                    self.model_config, self.mesh, params
                )
                return inner(packed, prompt_ids, prompt_mask, rng)

            return sampler

        model = self.model
        return make_seq2seq_sampler(
            lambda p, ids, mask: model.apply(
                {"params": p}, ids, mask, method=T5WithValueHead.encode
            ),
            lambda p, ids, **kw: model.apply(
                {"params": p}, ids, method=T5WithValueHead.decode, **kw
            ),
            lambda p, enc: model.apply(
                {"params": p}, enc, method=T5WithValueHead.init_cross_kv
            ),
            functools.partial(init_t5_cache, self.model_config),
            self.gen_config,
            with_values=True,
            cache_sharding=self._decode_cache_sharding(),
        )

    def _decoder_inputs(self, mb_response_tokens, mb_response_mask):
        pad = self.gen_config.pad_token_id
        start = self.gen_config.decoder_start_token_id
        dec_ids = shift_tokens_right(mb_response_tokens, pad, start)
        dec_mask = jnp.concatenate(
            [jnp.ones_like(mb_response_mask[:, :1]), mb_response_mask[:, :-1]], axis=1
        )
        return dec_ids, dec_mask

    def _forward_logprobs_values(self, params, mb: PPORolloutBatch):
        dec_ids, dec_mask = self._decoder_inputs(mb.response_tokens, mb.response_mask)
        if self.pp_stages > 1:
            from trlx_tpu.models.pp_runner import pp_t5_response_forward

            logits, values = pp_t5_response_forward(
                self.model_config, params, mb.query_tokens, mb.query_mask,
                dec_ids, dec_mask, self.mesh, self.pp_microbatches,
                virtual_stages=self.pp_virtual_stages,
                remat=self.pp_remat,
            )
            out = {"logits": logits, "values": values}
        else:
            out = self.model.apply(
                {"params": params},
                mb.query_tokens,
                attention_mask=mb.query_mask,
                decoder_input_ids=dec_ids,
                decoder_attention_mask=dec_mask,
            )
        logprobs = logprobs_from_logits(out["logits"], mb.response_tokens)
        # entropy also under health at ent_coef=0 (the entropy-collapse
        # detector's series), same contract as the causal trainer
        entropy = (
            _policy_entropy(out["logits"])
            if (self.config.method.ent_coef or self._health_enabled)
            else None
        )
        # no MoE T5 family: the 4th slot (router losses) is always None
        return logprobs, out["values"].astype(jnp.float32), entropy, None

    def _supports_hydra(self) -> bool:
        # the fork disables the hydra branch for T5 and uses a full frozen
        # copy (`ppo_orchestrator.py:41-43`)
        return False

    def _ref_logprobs(self, ref_params, policy_params, q_ids, q_mask, r_ids, r_mask):
        dec_ids, dec_mask = self._decoder_inputs(r_ids, r_mask)
        if self.pp_stages > 1:
            from trlx_tpu.models.pp_runner import pp_t5_ref_logits

            logits = pp_t5_ref_logits(
                self.model_config, ref_params, q_ids, q_mask,
                dec_ids, dec_mask, self.mesh, self.pp_microbatches,
                virtual_stages=self.pp_virtual_stages,
            )
            return logprobs_from_logits(logits, r_ids)
        out = self.backbone.apply(
            {"params": ref_params},
            q_ids,
            attention_mask=q_mask,
            decoder_input_ids=dec_ids,
            decoder_attention_mask=dec_mask,
        )
        return logprobs_from_logits(out["logits"], r_ids)
