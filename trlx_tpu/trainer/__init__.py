"""Trainer layer (reference layer 5, ``trlx/model/``).

``BaseRLTrainer`` re-designs ``BaseRLModel`` + ``AccelerateRLModel``
(``trlx/model/__init__.py:17-144``, ``accelerate_base_model.py:29-325``):
same responsibilities — own the model/optimizer/schedule, ``learn()`` /
``evaluate()`` / ``save()`` / ``load()``, log/eval/save cadence — but state
is an explicit pytree updated by jitted steps on a device mesh, not a
mutable module wrapped by Accelerate.
"""

from __future__ import annotations

import sys
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax
import numpy as np

from trlx_tpu.data.configs import TRLConfig

_TRAINERS: Dict[str, type] = {}


def register_trainer(name=None):
    """Decorator registering a trainer class (reference
    `trlx/model/__init__.py:14-36` ``register_model``)."""

    def register_class(cls, key: str):
        _TRAINERS[key] = cls
        setattr(sys.modules[__name__], key, cls)
        return cls

    if isinstance(name, type):
        return register_class(name, name.__name__.lower())

    def wrap(cls):
        return register_class(cls, (name or cls.__name__).lower())

    return wrap


def get_trainer(name: str) -> type:
    key = name.lower()
    if key not in _TRAINERS:
        import trlx_tpu.trainer.grpo_trainer  # noqa: F401
        import trlx_tpu.trainer.ilql_trainer  # noqa: F401
        import trlx_tpu.trainer.ppo_trainer  # noqa: F401
        import trlx_tpu.trainer.seq2seq_ppo_trainer  # noqa: F401
    if key in _TRAINERS:
        return _TRAINERS[key]
    raise ValueError(f"Unknown trainer: {name!r}. Registered: {sorted(_TRAINERS)}")


class BaseRLTrainer(ABC):
    def __init__(
        self,
        config: TRLConfig,
        reward_fn: Optional[Callable] = None,
        metric_fn: Optional[Callable] = None,
        tokenizer=None,
        logit_mask=None,
    ):
        self.config = config
        self.reward_fn = reward_fn
        self.metric_fn = metric_fn
        self.tokenizer = tokenizer
        self.logit_mask = logit_mask
        self.orch = None  # back-reference installed by the orchestrator
        self.eval_pipeline = None

    def add_eval_pipeline(self, pipeline) -> None:
        """Eval prompts source (reference `accelerate_base_model.py:148-150`)."""
        self.eval_pipeline = pipeline

    def intervals(self, step: int) -> Dict[str, bool]:
        """Log/eval/save cadence (reference `trlx/model/__init__.py:135-144`)."""
        t = self.config.train
        return {
            "do_log": step % t.log_interval == 0,
            "do_eval": step % t.eval_interval == 0,
            "do_save": step > 0 and step % t.checkpoint_interval == 0,
        }

    def _decode_cache_sharding(self):
        """KV-cache sharding for the compiled samplers: with an ``sp`` mesh
        axis > 1 the cache's *capacity* axis (causal) or the cross-KV's
        encoder-length axis (seq2seq) shards over sp, so long-context
        rollouts hold 1/sp of the cache per device (the training-side
        counterpart is ring attention, `ops/ring_attention.py`)."""
        from jax.sharding import NamedSharding, PartitionSpec

        from trlx_tpu.parallel.mesh import BATCH_AXES

        if dict(self.mesh.shape).get("sp", 1) <= 1:
            return None
        return NamedSharding(self.mesh, PartitionSpec(BATCH_AXES, "sp"))

    def setup_ep_axis(self, mesh, family) -> None:
        """Validate + install expert parallelism for this trainer's model.

        An ``ep`` mesh axis is only meaningful for families with switch-MoE
        experts (``ModelFamily.supports_ep``); for any other family the
        axis would silently replicate all compute, so reject it loudly. For
        MoE families, install the mesh as the module-level ep context
        (`models/gpt2_moe.py::set_ep_mesh`) — call this *after* parameter
        init (so init traces the dense path with no token-divisibility
        constraints) and *before* building jitted programs. One active MoE
        trainer per process: a second MoE trainer re-points the context.
        """
        ep = dict(mesh.shape).get("ep", 1)
        if ep > 1 and not getattr(family, "supports_ep", False):
            raise NotImplementedError(
                f"ep mesh axis requires an MoE family (supports_ep); "
                f"{family.name!r} has no experts to shard — the axis would "
                "silently replicate all compute"
            )
        if getattr(family, "supports_ep", False):
            from trlx_tpu.models import gpt2_moe

            gpt2_moe.set_ep_mesh(mesh)

    def check_anomalies(self, stats: Dict[str, Any], step: int) -> None:
        """Abort with a clear error when fetched loss stats go non-finite
        (``train.detect_anomalies``; beyond the reference — SURVEY §5.3
        records no failure detection). ``stats`` values may be scalars or
        stacked per-update rows; only host-side (already-fetched) values are
        examined, so the check costs no device round-trip."""
        if not self.config.train.detect_anomalies:
            return
        for key, v in stats.items():
            if not key.startswith("losses/"):
                continue
            arr = np.asarray(v, dtype=np.float64)
            finite = np.isfinite(arr)
            if not finite.all():
                if arr.ndim == 0:
                    at, value = step, float(arr)
                else:
                    # stacked per-update rows: `step` is the count *before*
                    # the fused pass, row r is update step + r + 1
                    first_bad = int(np.argmin(finite.ravel()))
                    at = step + first_bad + 1
                    value = float(arr.ravel()[first_bad])
                mesh_spec = ",".join(
                    f"{k}={v}" for k, v in dict(self.mesh.shape).items()
                    if v != 1
                )
                raise RuntimeError(
                    f"non-finite {key} ({value}) detected at step {at} — "
                    "training diverged. Localize the first NaN-minting "
                    "equation with `python -m trlx_tpu.analysis --sanitize "
                    f"<trainer> --mesh {mesh_spec or 'dp=1'}` "
                    "(docs/static_analysis.md), inspect the learning rate / "
                    "reward scale, or resume from the last checkpoint in "
                    f"{self.config.train.checkpoint_dir!r}."
                )

    @abstractmethod
    def learn(self) -> None: ...

    @abstractmethod
    def sample(self, prompt_ids, prompt_mask):
        """Run the trainer's compiled sampler on a prompt batch."""
        ...

    @abstractmethod
    def save(self, directory: Optional[str] = None) -> None: ...

    @abstractmethod
    def load(self, directory: str) -> None: ...

    # --- shared host-side text boundary -------------------------------- #

    def apply_tokenizer_gen_defaults(self, gen_kwargs: Dict[str, Any]) -> None:
        """Default eos/pad from the tokenizer when the config didn't set them
        (reference wires tokenizer ids into generate kwargs,
        `accelerate_ppo_model.py:50-54`). pad falls back to eos when the
        tokenizer has none; a pad id of 0 is preserved (is-not-None check)."""
        if self.tokenizer is None:
            return
        gen_kwargs.setdefault("eos_token_id", self.tokenizer.eos_token_id)
        gen_kwargs.setdefault(
            "pad_token_id",
            self.tokenizer.pad_token_id
            if self.tokenizer.pad_token_id is not None
            else self.tokenizer.eos_token_id,
        )

    def decode_responses(self, tokens, response_mask) -> List[str]:
        """Detokenize responses, truncated at their mask (host boundary).

        Both arrays come back in ONE transfer event: on a tunneled TPU a
        device->host fetch costs a flat ~100ms regardless of size, so two
        separate ``np.asarray`` calls would double the host-boundary tax
        (SURVEY §7.3)."""
        tokens, response_mask = jax.device_get((tokens, response_mask))
        lengths = response_mask.sum(axis=1)
        out = []
        for row, n in zip(tokens, lengths):
            ids = row[: int(n)].tolist()
            if self.tokenizer is not None:
                out.append(self.tokenizer.decode(ids, skip_special_tokens=True))
            else:
                out.append(" ".join(map(str, ids)))
        return out

    def decode_queries(self, q_ids, q_mask) -> List[str]:
        q_ids, q_mask = jax.device_get((q_ids, q_mask))
        out = []
        for row, m in zip(q_ids, q_mask):
            ids = row[np.asarray(m, bool)].tolist()
            if self.tokenizer is not None:
                out.append(self.tokenizer.decode(ids, skip_special_tokens=True))
            else:
                out.append(" ".join(map(str, ids)))
        return out

    def evaluate(self) -> Dict[str, Any]:
        """Sample eval prompts, score, and build a sample table (reference
        `accelerate_base_model.py:152-222`). Uses full fixed-size pad-filled
        batches so the compiled sampler is reused."""
        if self.eval_pipeline is None:
            return {}
        from trlx_tpu import telemetry

        with telemetry.span("phase/eval"):
            return self._evaluate_body()

    def _evaluate_body(self) -> Dict[str, Any]:
        from trlx_tpu.utils import Clock

        clock = Clock()
        all_queries, all_texts, all_gt = [], [], []
        # dispatch every eval chunk's sampler first (independent programs),
        # then pull all outputs in ONE transfer event — N fetch round-trips
        # (~100ms each on a tunneled chip) collapse into one
        chunks = []
        for batch, meta in self.eval_pipeline.create_loader(
            self.eval_batch_size, shuffle=False, drop_last=False
        ):
            out = self.sample(batch.input_ids, batch.attention_mask)
            # keep only what eval consumes — retaining full SampleOutputs
            # would pin every chunk's logprobs/values on device at once
            chunks.append((batch, meta, (out.tokens, out.response_mask)))
        fetched = jax.device_get([arrs for _, _, arrs in chunks])
        for (batch, meta, _), (tokens, response_mask) in zip(chunks, fetched):
            n_real = meta["n_real"]
            texts = self.decode_responses(tokens, response_mask)[:n_real]
            if meta["prompts_text"][0] is not None:
                queries = meta["prompts_text"][:n_real]
            else:
                queries = self.decode_queries(batch.input_ids, batch.attention_mask)[
                    :n_real
                ]
            all_queries += queries
            all_texts += texts
            if meta["response_gt"] is not None:
                all_gt += meta["response_gt"][:n_real]
        generate_time = clock.tick() / 1000.0

        stats: Dict[str, Any] = {"time/generate": generate_time}
        columns = ["query", "response"]
        table = [list(t) for t in zip(all_queries, all_texts)]
        if self.reward_fn is not None:
            scores = np.asarray(
                self.reward_fn(
                    samples=all_texts,
                    queries=all_queries,
                    response_gt=all_gt if all_gt else None,
                ),
                dtype=np.float32,
            )
            stats["reward/mean"] = float(scores.mean())
            stats["reward/std"] = float(scores.std())
            columns.append("reward")
            table = [row + [float(s)] for row, s in zip(table, scores)]
        if self.metric_fn is not None:
            metric_clock = Clock()
            metrics = self.metric_fn(all_texts)
            for k, v in metrics.items():
                v = np.asarray(v, dtype=np.float32)
                stats[f"metrics/{k}"] = float(v.mean())
            # reference logs metric_time (`accelerate_base_model.py:202-204`)
            stats["time/metric"] = metric_clock.tick() / 1000.0
        self._last_samples = (columns, table)
        return stats

    @property
    def eval_batch_size(self) -> int:
        return getattr(self.config.method, "chunk_size", None) or self.config.train.batch_size
