"""Trainer layer (reference layer 5, ``trlx/model/``).

``BaseRLTrainer`` re-designs ``BaseRLModel`` + ``AccelerateRLModel``
(``trlx/model/__init__.py:17-144``, ``accelerate_base_model.py:29-325``):
same responsibilities — own the model/optimizer/schedule, ``learn()`` /
``evaluate()`` / ``save()`` / ``load()``, log/eval/save cadence — but state
is an explicit pytree updated by jitted steps on a device mesh, not a
mutable module wrapped by Accelerate.
"""

from __future__ import annotations

import sys
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Iterable, Optional

from trlx_tpu.data.configs import TRLConfig

_TRAINERS: Dict[str, type] = {}


def register_trainer(name=None):
    """Decorator registering a trainer class (reference
    `trlx/model/__init__.py:14-36` ``register_model``)."""

    def register_class(cls, key: str):
        _TRAINERS[key] = cls
        setattr(sys.modules[__name__], key, cls)
        return cls

    if isinstance(name, type):
        return register_class(name, name.__name__.lower())

    def wrap(cls):
        return register_class(cls, (name or cls.__name__).lower())

    return wrap


def get_trainer(name: str) -> type:
    key = name.lower()
    if key not in _TRAINERS:
        import trlx_tpu.trainer.ppo_trainer  # noqa: F401

        try:
            import trlx_tpu.trainer.ilql_trainer  # noqa: F401
        except ImportError:
            pass
    if key in _TRAINERS:
        return _TRAINERS[key]
    raise ValueError(f"Unknown trainer: {name!r}. Registered: {sorted(_TRAINERS)}")


class BaseRLTrainer(ABC):
    def __init__(
        self,
        config: TRLConfig,
        reward_fn: Optional[Callable] = None,
        metric_fn: Optional[Callable] = None,
        tokenizer=None,
        logit_mask=None,
    ):
        self.config = config
        self.reward_fn = reward_fn
        self.metric_fn = metric_fn
        self.tokenizer = tokenizer
        self.logit_mask = logit_mask
        self.orch = None  # back-reference installed by the orchestrator
        self.eval_pipeline = None

    def add_eval_pipeline(self, pipeline) -> None:
        """Eval prompts source (reference `accelerate_base_model.py:148-150`)."""
        self.eval_pipeline = pipeline

    def intervals(self, step: int) -> Dict[str, bool]:
        """Log/eval/save cadence (reference `trlx/model/__init__.py:135-144`)."""
        t = self.config.train
        return {
            "do_log": step % t.log_interval == 0,
            "do_eval": step % t.eval_interval == 0,
            "do_save": step > 0 and step % t.checkpoint_interval == 0,
        }

    @abstractmethod
    def learn(self) -> None: ...

    @abstractmethod
    def evaluate(self) -> Dict[str, Any]: ...

    @abstractmethod
    def save(self, directory: Optional[str] = None) -> None: ...

    @abstractmethod
    def load(self, directory: str) -> None: ...
