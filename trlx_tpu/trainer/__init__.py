"""Trainer layer (reference layer 5, ``trlx/model/``).

``BaseRLTrainer`` re-designs ``BaseRLModel`` + ``AccelerateRLModel``
(``trlx/model/__init__.py:17-144``, ``accelerate_base_model.py:29-325``):
same responsibilities — own the model/optimizer/schedule, ``learn()`` /
``evaluate()`` / ``save()`` / ``load()``, log/eval/save cadence — but state
is an explicit pytree updated by jitted steps on a device mesh, not a
mutable module wrapped by Accelerate.
"""

from __future__ import annotations

import sys
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax
import numpy as np

from trlx_tpu.data.configs import TRLConfig

_TRAINERS: Dict[str, type] = {}


def register_trainer(name=None):
    """Decorator registering a trainer class (reference
    `trlx/model/__init__.py:14-36` ``register_model``)."""

    def register_class(cls, key: str):
        _TRAINERS[key] = cls
        setattr(sys.modules[__name__], key, cls)
        return cls

    if isinstance(name, type):
        return register_class(name, name.__name__.lower())

    def wrap(cls):
        return register_class(cls, (name or cls.__name__).lower())

    return wrap


def get_trainer(name: str) -> type:
    key = name.lower()
    if key not in _TRAINERS:
        import trlx_tpu.trainer.grpo_trainer  # noqa: F401
        import trlx_tpu.trainer.ilql_trainer  # noqa: F401
        import trlx_tpu.trainer.ppo_trainer  # noqa: F401
        import trlx_tpu.trainer.seq2seq_ppo_trainer  # noqa: F401
    if key in _TRAINERS:
        return _TRAINERS[key]
    raise ValueError(f"Unknown trainer: {name!r}. Registered: {sorted(_TRAINERS)}")


class BaseRLTrainer(ABC):
    def __init__(
        self,
        config: TRLConfig,
        reward_fn: Optional[Callable] = None,
        metric_fn: Optional[Callable] = None,
        tokenizer=None,
        logit_mask=None,
    ):
        self.config = config
        self.reward_fn = reward_fn
        self.metric_fn = metric_fn
        self.tokenizer = tokenizer
        self.logit_mask = logit_mask
        self.orch = None  # back-reference installed by the orchestrator
        self.eval_pipeline = None
        from trlx_tpu import telemetry

        # span-ring capacity (train.telemetry.ring_size,
        # docs/observability.md): sized before any phase emits spans
        telemetry.configure_from_dict(
            getattr(config.train, "telemetry", None)
        )
        self._setup_health()

    def _setup_health(self) -> None:
        """Run-health monitoring (telemetry/health.py): parse
        ``train.health``, and — when enabled, on the main process only
        (a per-host abort decision would desynchronize the collective
        schedule, the host-branch hazard) — build the detector monitor
        and the crash-forensics flight recorder. ``_health_enabled``
        additionally gates the fused device-side health scalars in the
        jitted steps, so it is set before any program is built."""
        from trlx_tpu.telemetry.health import HealthConfig

        health_dict = dict(self.config.train.health or {})
        async_dict = dict(getattr(self.config.train, "async_rl", None) or {})
        if async_dict.get("enabled") and health_dict.get("enabled"):
            # async actor–learner circuit-breaker: the staleness-breach
            # detector's threshold IS the configured staleness window
            # unless the user tuned it explicitly — a guard bug (not
            # ordinary operation) is the only way to cross it
            detectors = dict(health_dict.get("detectors") or {})
            if "staleness-breach" not in detectors:
                detectors["staleness-breach"] = {
                    "threshold": float(
                        async_dict.get("staleness_window", 1)
                    )
                }
                health_dict["detectors"] = detectors
        self.health_config = HealthConfig.from_dict(health_dict)
        self._health_enabled = bool(self.health_config.enabled)
        self._health_ev = True  # GRPO opts out (placeholder returns slot)
        self.health_monitor = None
        self.flight_recorder = None
        self._phase_log = None  # run_dir live --watch feed (run_ledger.py)
        if not self._health_enabled:
            return
        from trlx_tpu.parallel.distributed import is_main_process

        if not is_main_process():
            return
        from trlx_tpu.telemetry.flight_recorder import FlightRecorder
        from trlx_tpu.telemetry.health import (
            HealthMonitor,
            config_fingerprint,
        )

        config_dict = self.config.to_dict()
        fingerprint = config_fingerprint(config_dict)
        self.health_monitor = HealthMonitor(self.health_config, fingerprint)
        self.flight_recorder = FlightRecorder(
            capacity=self.health_config.flight_capacity,
            directory=self.health_config.dump_dir,
            fingerprint=fingerprint,
            config=config_dict,
        )
        # live phase-row mirror for `--watch` (run_ledger.py): rides the
        # flight recorder's phase records, so it shares its gating
        # (health.enabled + rank 0)
        run_dir = getattr(self.config.train, "run_dir", None)
        if run_dir:
            from trlx_tpu.telemetry.run_ledger import PhaseLogWriter

            self._phase_log = PhaseLogWriter(run_dir)

    def observe_health(
        self,
        row: Dict[str, Any],
        step: Optional[int] = None,
        phase: Optional[int] = None,
    ) -> None:
        """Feed one already-fetched stats row to the detector engine.

        Called wherever rows cross to host anyway (the streamed phase
        epilogue, the fused pass, log steps on the stepwise path, ILQL
        chunks, the orchestrator's collect stats) — the monitor never
        forces a device transfer; ``jax.Array`` leaves are skipped and
        observed later from the row they are fetched into. Each trip
        lands in the span stream and the Logger; ``error`` trips apply
        the ``health.on_error`` policy (warn | dump | abort)."""
        monitor = self.health_monitor
        if monitor is None:
            return
        from trlx_tpu import telemetry

        events = monitor.observe(row, step=step, phase=phase)
        if not events:
            return
        logger = getattr(self, "logger", None)
        for ev in events:
            # zero-length marker span: the trip shows on the trace
            # timeline next to the phase whose stats produced it
            with telemetry.span(
                "health/" + ev.detector,
                severity=ev.severity,
                series=ev.series,
                step=ev.step,
            ):
                pass
            if logger is not None:
                logger.log_health_event(ev.to_dict(), step=ev.step)
            else:
                print(f"health: {ev.severity} {ev.detector}: {ev.message}",
                      file=sys.stderr)
        errors = [ev for ev in events if ev.severity == "error"]
        policy = self.health_config.on_error
        if not errors or policy == "warn":
            return
        recorder = self.flight_recorder
        if recorder is not None:
            # land the OFFENDING row + its events in the ring before
            # dumping, so the forensics file's final phase record and
            # its last-good diff show the anomaly itself — the phase
            # epilogue's own record has not run yet at this point.
            # Guarded: under the record-and-continue `dump` policy a
            # failing forensics write (full disk, unserializable config)
            # must never kill an otherwise-continuable run
            try:
                recorder.record_phase(
                    phase,
                    step=errors[0].step,
                    stats_row=row,
                    events=events,
                    detector_state=monitor.state_summary(),
                )
                for ev in errors:
                    path = recorder.dump(
                        "detector:" + ev.detector, once=True
                    )
                    if path:
                        print(f"health: flight record dumped to {path}",
                              file=sys.stderr)
            except Exception as dump_err:
                print(
                    f"health: flight dump FAILED "
                    f"({type(dump_err).__name__}: {dump_err})",
                    file=sys.stderr,
                )
        if policy == "abort":
            from trlx_tpu.telemetry.health import HealthAbort

            first = errors[0]
            raise HealthAbort(
                f"health.on_error=abort: detector {first.detector!r} "
                f"tripped at step {first.step} ({first.message}); "
                f"flight record(s): {self.flight_recorder.dumped if self.flight_recorder else 'disabled'}"
            )

    def observe_health_rows(
        self,
        rows: Dict[str, Any],
        step0: Optional[int] = None,
        phase: Optional[int] = None,
        phase_row: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Feed a fetched stacked-stats tree (each value an [n_updates]
        host array) to the detectors row by row, then ``phase_row`` —
        series that are constant across the phase's rows (the rollout
        KL) — exactly ONCE. Repeating a phase-constant value per row
        would collapse its EWMA variance and burn warmup/cooldown in
        row units, hair-triggering the z-score rules on ordinary
        phase-to-phase movement. Returns the last row (+ phase_row)
        for the flight record."""
        last: Dict[str, Any] = {}
        if self.health_monitor is None or not rows:
            return last
        n_rows = len(rows[next(iter(rows))])
        for r in range(n_rows):
            last = {key: float(v[r]) for key, v in rows.items()}
            self.observe_health(
                last,
                step=None if step0 is None else step0 + r + 1,
                phase=phase,
            )
        if phase_row:
            self.observe_health(phase_row, phase=phase)
            last = {**last, **phase_row}
        return last

    def emit_health_event(
        self,
        detector: str,
        severity: str,
        message: str,
        series: str = "resilience",
        value: float = 1.0,
        step: Optional[int] = None,
        phase: Optional[int] = None,
    ) -> None:
        """Record one host-originated health event (engine fallback and
        other graceful degradations, docs/resilience.md) through the
        same sinks a detector trip uses: the monitor's event log, a
        zero-length marker span, and the Logger's ``health_event`` JSON
        line. Unlike :meth:`observe_health` this never applies the
        ``health.on_error`` policy — degradations are the alternative
        to aborting, not a trigger for it."""
        from trlx_tpu import telemetry
        from trlx_tpu.telemetry.health import HealthEvent

        monitor = self.health_monitor
        ev = HealthEvent(
            detector=detector,
            severity=severity,
            series=series,
            value=float(value),
            step=int(step) if step is not None else -1,
            phase=phase,
            message=message,
            fingerprint=monitor.fingerprint if monitor is not None else "",
        )
        if monitor is not None:
            monitor.events.append(ev)
            monitor.event_counts[detector] = (
                monitor.event_counts.get(detector, 0) + 1
            )
        with telemetry.span(
            "health/" + detector,
            severity=severity,
            series=series,
            step=ev.step,
        ):
            pass
        logger = getattr(self, "logger", None)
        if logger is not None:
            logger.log_health_event(ev.to_dict(), step=step)
        else:
            print(
                f"health: {severity} {detector}: {message}", file=sys.stderr
            )

    def maybe_drain(
        self, phase: Optional[int] = None, step: Optional[int] = None
    ) -> None:
        """Phase-boundary resilience hook (docs/resilience.md): the
        ``slow_step`` / ``preempt`` fault-injection sites, then — when a
        guarded SIGTERM/SIGINT arrived since the last boundary — the
        graceful drain: write an emergency atomic checkpoint (the same
        save path as the cadence checkpoint, retried on transient I/O),
        dump the flight recorder, and raise
        :class:`~trlx_tpu.resilience.preemption.PreemptionDrain` for
        the supervisor / a distinct exit code. Costs one flag read per
        phase when no guard is installed."""
        from trlx_tpu.resilience import chaos, preemption

        chaos.check("slow_step", phase=phase, step=step)
        chaos.check("preempt", phase=phase, step=step)
        if not preemption.drain_requested():
            return
        from trlx_tpu.utils.checkpoint import wait_for_checkpoints

        directory = self.config.train.checkpoint_dir
        print(
            f"resilience: draining at phase boundary (step {step}) — "
            f"writing emergency checkpoint to {directory!r}",
            file=sys.stderr,
        )
        self.save()
        wait_for_checkpoints()  # the drain's whole point is durability
        recorder = self.flight_recorder
        if recorder is not None:
            try:
                path = recorder.dump("preemption", once=True)
                if path:
                    print(
                        f"health: flight record dumped to {path}",
                        file=sys.stderr,
                    )
            except Exception:
                pass  # forensics must never block the drain
        raise preemption.PreemptionDrain(
            f"preempted ({preemption.received_signal()}): drained at "
            f"step {step} with an emergency checkpoint in {directory!r}",
            step=step,
            checkpoint_dir=directory,
        )

    def record_flight_phase(
        self,
        phase: Optional[int],
        step: Optional[int] = None,
        stats_row: Optional[Dict[str, Any]] = None,
        kl_seq: Optional[List[float]] = None,
    ) -> None:
        """Append one phase record to the flight ring (no-op when health
        is off) and honor the on-demand ``train.flight_dump_phase``."""
        recorder = self.flight_recorder
        if recorder is None:
            return
        monitor = self.health_monitor
        rec = recorder.record_phase(
            phase,
            step=step,
            stats_row=stats_row,
            kl_seq=kl_seq,
            events=monitor.recent_events(phase) if monitor else (),
            detector_state=monitor.state_summary() if monitor else None,
        )
        if self._phase_log is not None:
            # the live --watch feed: the same record, minus the
            # detector EWMA state (bulky and meaningless line-by-line)
            self._phase_log.append(
                {k: v for k, v in rec.items() if k != "detectors"}
            )
        want = self.config.train.flight_dump_phase
        if want is not None and phase == want:
            path = recorder.dump(f"flight_dump_phase:{phase}", once=True)
            if path:
                print(f"health: flight record dumped to {path}",
                      file=sys.stderr)

    def flight_dump_on_exception(self, error: BaseException) -> None:
        """learn()-epilogue hook: write the crash forensics file for an
        uncaught exception (at most once per recorder; a HealthAbort
        whose detector already dumped is not dumped again)."""
        recorder = self.flight_recorder
        if recorder is None:
            return
        try:
            monitor = self.health_monitor
            if monitor is not None and monitor.events:
                # fold events the crash preempted out of a phase record
                # (e.g. check_anomalies raising mid-epilogue) into the
                # NEWEST record — never a fresh stats-less one, which
                # would displace the real final phase from the
                # --inspect last-good diff; the recorder dedupes, so
                # repeats are safe
                recorder.note_events(
                    monitor.events,
                    detector_state=monitor.state_summary(),
                )
            path = recorder.dump_on_exception(error)
        except Exception:
            return  # forensics must never mask the real failure
        if path:
            print(f"health: flight record dumped to {path}", file=sys.stderr)

    def append_run_ledger(
        self,
        status: str = "ok",
        error: Optional[BaseException] = None,
        payload: Optional[Dict[str, Any]] = None,
    ) -> None:
        """learn()-epilogue hook (docs/observability.md "Run ledger"):
        append this run's :class:`RunManifest` — config fingerprint,
        platform, git sha, span stats, metrics snapshot, health-event
        counts, final stats — to the ledger JSONL, and write
        ``<run_dir>/manifest.json`` when ``train.run_dir`` is set.
        Active only when ``train.run_dir`` or ``$TRLX_RUN_LEDGER`` is
        configured; best-effort (a full disk must never mask the run's
        real outcome)."""
        import os

        run_dir = getattr(self.config.train, "run_dir", None)
        ledger_env = os.environ.get("TRLX_RUN_LEDGER")
        if not run_dir and not ledger_env:
            return
        try:
            from trlx_tpu.parallel.distributed import is_main_process

            if not is_main_process():
                return
            import json

            from trlx_tpu.telemetry.run_ledger import (
                append_manifest,
                build_manifest,
                numeric_payload,
            )

            body = dict(payload or {})
            body["status"] = status
            if error is not None:
                body["error"] = f"{type(error).__name__}: {error}"
            body.update(
                numeric_payload(getattr(self, "_final_stats", None) or {})
            )
            monitor = self.health_monitor
            manifest = build_manifest(
                kind=f"train/{type(self).__name__}",
                config=self.config.to_dict(),
                payload=body,
                health_events=(
                    dict(monitor.event_counts) if monitor is not None else {}
                ),
            )
            ledger = ledger_env or (
                os.path.join(run_dir, "ledger.jsonl") if run_dir else None
            )
            if ledger:
                append_manifest(manifest, ledger)
            if run_dir:
                os.makedirs(run_dir, exist_ok=True)
                with open(
                    os.path.join(run_dir, "manifest.json"),
                    "w",
                    encoding="utf-8",
                ) as fh:
                    json.dump(manifest, fh, default=float)
        except Exception as e:
            print(
                f"run_ledger: manifest append failed "
                f"({type(e).__name__}: {e})",
                file=sys.stderr,
            )

    def add_eval_pipeline(self, pipeline) -> None:
        """Eval prompts source (reference `accelerate_base_model.py:148-150`)."""
        self.eval_pipeline = pipeline

    def intervals(self, step: int) -> Dict[str, bool]:
        """Log/eval/save cadence (reference `trlx/model/__init__.py:135-144`)."""
        t = self.config.train
        return {
            "do_log": step % t.log_interval == 0,
            "do_eval": step % t.eval_interval == 0,
            "do_save": step > 0 and step % t.checkpoint_interval == 0,
        }

    def _decode_cache_sharding(self):
        """KV-cache sharding for the compiled samplers: with an ``sp`` mesh
        axis > 1 the cache's *capacity* axis (causal) or the cross-KV's
        encoder-length axis (seq2seq) shards over sp, so long-context
        rollouts hold 1/sp of the cache per device (the training-side
        counterpart is ring attention, `ops/ring_attention.py`)."""
        from jax.sharding import NamedSharding, PartitionSpec

        from trlx_tpu.parallel.mesh import BATCH_AXES

        if dict(self.mesh.shape).get("sp", 1) <= 1:
            return None
        return NamedSharding(self.mesh, PartitionSpec(BATCH_AXES, "sp"))

    def setup_ep_axis(self, mesh, family) -> None:
        """Validate + install expert parallelism for this trainer's model.

        An ``ep`` mesh axis is only meaningful for families with switch-MoE
        experts (``ModelFamily.supports_ep``); for any other family the
        axis would silently replicate all compute, so reject it loudly. For
        MoE families, install the mesh as the module-level ep context
        (`models/gpt2_moe.py::set_ep_mesh`) — call this *after* parameter
        init (so init traces the dense path with no token-divisibility
        constraints) and *before* building jitted programs. One active MoE
        trainer per process: a second MoE trainer re-points the context.
        """
        ep = dict(mesh.shape).get("ep", 1)
        if ep > 1 and not getattr(family, "supports_ep", False):
            raise NotImplementedError(
                f"ep mesh axis requires an MoE family (supports_ep); "
                f"{family.name!r} has no experts to shard — the axis would "
                "silently replicate all compute"
            )
        if getattr(family, "supports_ep", False):
            from trlx_tpu.models import gpt2_moe

            gpt2_moe.set_ep_mesh(mesh)

    def check_anomalies(self, stats: Dict[str, Any], step: int) -> None:
        """Abort with a clear error when fetched loss stats go non-finite
        (``train.detect_anomalies``; beyond the reference — SURVEY §5.3
        records no failure detection). ``stats`` values may be scalars or
        stacked per-update rows; only host-side (already-fetched) values are
        examined, so the check costs no device round-trip."""
        if not self.config.train.detect_anomalies:
            return
        for key, v in stats.items():
            if not key.startswith("losses/"):
                continue
            arr = np.asarray(v, dtype=np.float64)
            finite = np.isfinite(arr)
            if not finite.all():
                if arr.ndim == 0:
                    at, value = step, float(arr)
                else:
                    # stacked per-update rows: `step` is the count *before*
                    # the fused pass, row r is update step + r + 1
                    first_bad = int(np.argmin(finite.ravel()))
                    at = step + first_bad + 1
                    value = float(arr.ravel()[first_bad])
                mesh_spec = ",".join(
                    f"{k}={v}" for k, v in dict(self.mesh.shape).items()
                    if v != 1
                )
                raise RuntimeError(
                    f"non-finite {key} ({value}) detected at step {at} — "
                    "training diverged. Localize the first NaN-minting "
                    "equation with `python -m trlx_tpu.analysis --sanitize "
                    f"<trainer> --mesh {mesh_spec or 'dp=1'}` "
                    "(docs/static_analysis.md), inspect the learning rate / "
                    "reward scale, or resume from the last checkpoint in "
                    f"{self.config.train.checkpoint_dir!r}."
                )

    @abstractmethod
    def learn(self) -> None: ...

    @abstractmethod
    def sample(self, prompt_ids, prompt_mask):
        """Run the trainer's compiled sampler on a prompt batch."""
        ...

    @abstractmethod
    def save(self, directory: Optional[str] = None) -> None: ...

    @abstractmethod
    def load(self, directory: str) -> None: ...

    # --- host-state resume contract ------------------------------------ #

    def host_state_dict(self) -> Dict[str, Any]:
        """Mutable *host* state that must survive kill/resume but lives
        outside the device pytree: every subclass folds its own entries
        on top of this dict and the result rides the checkpoint
        ``metadata`` pickle. The checkpoint/resume auditor (engine 15,
        ``python -m trlx_tpu.analysis --resume-audit``) statically
        requires each phase-loop-mutated attribute to be reachable from
        here, reconstructed from config, or allowlisted ephemeral — add
        new mutable schedule state to this dict, not just to save().

        The base contribution is the health-detector engine: its EWMA
        baselines and cooldowns decide post-resume alerting (see
        HealthMonitor.state_dict)."""
        state: Dict[str, Any] = {}
        if self.health_monitor is not None:
            state["health_monitor"] = self.health_monitor.state_dict()
        return state

    def load_host_state_dict(self, state: Dict[str, Any]) -> None:
        """Inverse of :meth:`host_state_dict`; tolerates missing keys so
        checkpoints written before a given piece of state existed still
        restore (the schema lock in analysis/budgets.json makes any
        *removal* loud instead)."""
        monitor_state = state.get("health_monitor")
        if monitor_state is not None and self.health_monitor is not None:
            self.health_monitor.load_state_dict(monitor_state)

    # --- shared host-side text boundary -------------------------------- #

    def apply_tokenizer_gen_defaults(self, gen_kwargs: Dict[str, Any]) -> None:
        """Default eos/pad from the tokenizer when the config didn't set them
        (reference wires tokenizer ids into generate kwargs,
        `accelerate_ppo_model.py:50-54`). pad falls back to eos when the
        tokenizer has none; a pad id of 0 is preserved (is-not-None check)."""
        if self.tokenizer is None:
            return
        gen_kwargs.setdefault("eos_token_id", self.tokenizer.eos_token_id)
        gen_kwargs.setdefault(
            "pad_token_id",
            self.tokenizer.pad_token_id
            if self.tokenizer.pad_token_id is not None
            else self.tokenizer.eos_token_id,
        )

    def decode_responses(self, tokens, response_mask) -> List[str]:
        """Detokenize responses, truncated at their mask (host boundary).

        Both arrays come back in ONE transfer event: on a tunneled TPU a
        device->host fetch costs a flat ~100ms regardless of size, so two
        separate ``np.asarray`` calls would double the host-boundary tax
        (SURVEY §7.3)."""
        tokens, response_mask = jax.device_get((tokens, response_mask))
        lengths = response_mask.sum(axis=1)
        out = []
        for row, n in zip(tokens, lengths):
            ids = row[: int(n)].tolist()
            if self.tokenizer is not None:
                out.append(self.tokenizer.decode(ids, skip_special_tokens=True))
            else:
                out.append(" ".join(map(str, ids)))
        return out

    def decode_queries(self, q_ids, q_mask) -> List[str]:
        q_ids, q_mask = jax.device_get((q_ids, q_mask))
        out = []
        for row, m in zip(q_ids, q_mask):
            ids = row[np.asarray(m, bool)].tolist()
            if self.tokenizer is not None:
                out.append(self.tokenizer.decode(ids, skip_special_tokens=True))
            else:
                out.append(" ".join(map(str, ids)))
        return out

    def evaluate(self) -> Dict[str, Any]:
        """Sample eval prompts, score, and build a sample table (reference
        `accelerate_base_model.py:152-222`). Uses full fixed-size pad-filled
        batches so the compiled sampler is reused."""
        if self.eval_pipeline is None:
            return {}
        from trlx_tpu import telemetry

        with telemetry.span("phase/eval"):
            return self._evaluate_body()

    def _evaluate_body(self) -> Dict[str, Any]:
        from trlx_tpu.utils import Clock

        clock = Clock()
        all_queries, all_texts, all_gt = [], [], []
        # dispatch every eval chunk's sampler first (independent programs),
        # then pull all outputs in ONE transfer event — N fetch round-trips
        # (~100ms each on a tunneled chip) collapse into one
        chunks = []
        for batch, meta in self.eval_pipeline.create_loader(
            self.eval_batch_size, shuffle=False, drop_last=False
        ):
            out = self.sample(batch.input_ids, batch.attention_mask)
            # keep only what eval consumes — retaining full SampleOutputs
            # would pin every chunk's logprobs/values on device at once
            chunks.append((batch, meta, (out.tokens, out.response_mask)))
        fetched = jax.device_get([arrs for _, _, arrs in chunks])
        for (batch, meta, _), (tokens, response_mask) in zip(chunks, fetched):
            n_real = meta["n_real"]
            texts = self.decode_responses(tokens, response_mask)[:n_real]
            if meta["prompts_text"][0] is not None:
                queries = meta["prompts_text"][:n_real]
            else:
                queries = self.decode_queries(batch.input_ids, batch.attention_mask)[
                    :n_real
                ]
            all_queries += queries
            all_texts += texts
            if meta["response_gt"] is not None:
                all_gt += meta["response_gt"][:n_real]
        generate_time = clock.tick() / 1000.0

        stats: Dict[str, Any] = {"time/generate": generate_time}
        columns = ["query", "response"]
        table = [list(t) for t in zip(all_queries, all_texts)]
        if self.reward_fn is not None:
            scores = np.asarray(
                self.reward_fn(
                    samples=all_texts,
                    queries=all_queries,
                    response_gt=all_gt if all_gt else None,
                ),
                dtype=np.float32,
            )
            stats["reward/mean"] = float(scores.mean())
            stats["reward/std"] = float(scores.std())
            columns.append("reward")
            table = [row + [float(s)] for row, s in zip(table, scores)]
        if self.metric_fn is not None:
            metric_clock = Clock()
            metrics = self.metric_fn(all_texts)
            for k, v in metrics.items():
                v = np.asarray(v, dtype=np.float32)
                stats[f"metrics/{k}"] = float(v.mean())
            # reference logs metric_time (`accelerate_base_model.py:202-204`)
            stats["time/metric"] = metric_clock.tick() / 1000.0
        self._last_samples = (columns, table)
        return stats

    @property
    def eval_batch_size(self) -> int:
        return getattr(self.config.method, "chunk_size", None) or self.config.train.batch_size
