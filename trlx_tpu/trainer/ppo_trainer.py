"""PPO trainer: jitted rollout sampling + jitted PPO updates over a mesh.

Re-design of ``AcceleratePPOModel`` (``trlx/model/accelerate_ppo_model.py``)
+ the training loop of ``AccelerateRLModel.learn``
(``accelerate_base_model.py:224-305``):

- The policy (backbone + value head) lives as a sharded param pytree in an
  explicit :class:`TrainState`; the frozen KL reference model is a second
  (backbone-only) param pytree — the fork's full-frozen-copy path
  (`ppo_orchestrator.py:41-43`) with no second process-visible module.
- ``loss()`` (`accelerate_ppo_model.py:79-128`) becomes one jitted
  ``train_step``: policy forward, response logprobs/values, GAE (reversed
  ``lax.scan``), clipped surrogate, grads, optax update — gradient sync is
  the psum GSPMD inserts for the sharded batch; there is no
  ``accelerator.backward``.
- Generation is the compiled sampler from ``ops/sampling.py``; behavior
  logprobs and values are emitted during decode, so the orchestrator's
  policy-recompute forward disappears.
- The KL coefficient is host loop state updated per batch via the adaptive
  controller (`accelerate_ppo_model.py:136-137`), passed into the reward
  computation as a device scalar (no retrace).

Model-family specifics (forward slicing, sampler construction, checkpoint
conversion) are isolated in overridable hooks; the seq2seq (T5/UL2) variant
lives in ``seq2seq_ppo_trainer.py``.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from trlx_tpu import telemetry
from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.data.ppo_types import PPORolloutBatch
from trlx_tpu.models.heads import CausalLMWithValueHead
from trlx_tpu.ops.ppo_math import (
    PPOConfig,
    get_advantages_and_returns,
    kl_controller_update,
    policy_entropy,
    ppo_loss,
    reward_health_stats,
)
from trlx_tpu.ops.sampling import (
    GenerationConfig,
    SampleOutput,
    make_sampler,
    validate_gen_config,
)
from trlx_tpu.parallel import (
    batch_sharding,
    logprobs_from_logits,
    make_partition_specs,
    make_mesh,
    replicated,
)
from trlx_tpu.pipeline.ppo_buffer import (
    PPORolloutBuffer,
    StreamPlan,
    make_stream_plan,
)
from trlx_tpu.trainer import BaseRLTrainer, register_trainer
from trlx_tpu.trainer.common import (
    TrainState,
    make_optimizer,
    stop_frozen_gradients,
    unfrozen_param_mask,
)
from trlx_tpu.utils import Clock, set_seed
from trlx_tpu.utils.checkpoint import (
    has_checkpoint,
    load_checkpoint,
    save_checkpoint,
    wait_for_checkpoints,
)
from trlx_tpu.utils.logging import Logger


def get_causal_arch(config: TRLConfig):
    """(family, arch config, optional converted checkpoint params) for the
    configured causal model_type (reference ``get_arch``,
    `accelerate_ppo_model.py:56-59`, generalized over gpt2/gptj/gpt_neox)."""
    from trlx_tpu.models.registry import get_model_family

    family = get_model_family(config.model.model_type)
    overrides = dict(config.model.model_arch)
    overrides.setdefault("dtype", config.train.dtype)
    overrides.setdefault("param_dtype", config.train.param_dtype)
    if config.model.model_path:
        arch, params = family.load_checkpoint(
            config.model.model_path, dtype=config.train.param_dtype
        )
        arch = type(arch)(
            **{
                **arch.__dict__,
                "dtype": overrides["dtype"],
                "param_dtype": overrides["param_dtype"],
            }
        )
        return family, arch, params
    return family, family.config_cls.from_dict(overrides), None


def get_gpt2_arch(config: TRLConfig):
    """Back-compat shim; prefer :func:`get_causal_arch`."""
    _, arch, params = get_causal_arch(config)
    return arch, params


# canonical definition lives in ops/ppo_math.py (shared with ilql_loss);
# the underscore alias keeps this module's historical import surface
# (seq2seq_ppo_trainer imports it from here)
_policy_entropy = policy_entropy


class _StreamedPhase:
    """Host-side state of one streamed collect→train phase
    (docs/async_pipeline.md): the fixed update plan, the dispatch cursor
    over epoch-1 minibatches, their pending stats, and the monotonic
    marks (the tracer's clock) the overlap attribution is computed
    from."""

    def __init__(self, plan: StreamPlan, overlap: bool):
        self.plan = plan
        self.overlap = overlap
        self.next_mb = 0  # epoch-1 minibatches dispatched so far
        self.epoch1_stats: List[Dict[str, jax.Array]] = []
        self.t_first_dispatch: Optional[float] = None
        self.dispatched_during_collect = 0


class _AsyncStreamedPhase(_StreamedPhase):
    """The asynchronous actor–learner phase's extra host state
    (trainer/async_rl.py): the learner's update-version counter, the
    per-consumed-minibatch staleness record, guard-hold / learner-busy
    wall time (the ``async/*`` attribution stats), and the weight-push
    count. The underlying plan/dispatch machinery is the streamed
    phase's — async is a *policy* over when dispatches happen and what
    params the actors hold, never a different schedule."""

    def __init__(self, plan: StreamPlan, overlap: bool):
        super().__init__(plan, overlap)
        self.learner_version = 0
        self.staleness: List[int] = []  # in-flight lag per consumed mb
        self.consumed_lag: List[int] = []  # row age at consumption
        self.weight_pushes = 0
        self.guard_hold_ms = 0.0  # row-ready time spent behind the guard
        self.t_guard_hold: Optional[float] = None
        self.learner_busy_ms = 0.0  # epoch-1 dispatch spans (+ residual)
        self.t_begin = telemetry.monotonic()
        # set by finish_streamed_phase before the forced drain: rollouts
        # still in flight then (a chunk-rounded over-submission) can
        # never land into THIS plan, so they neither hold the staleness
        # accounting nor deserve further weight pushes
        self.collect_done = False


@register_trainer
class PPOTrainer(BaseRLTrainer):
    # param-tree key holding the (KL-reference) backbone
    backbone_key = "transformer"

    def __init__(
        self,
        config: TRLConfig,
        reward_fn: Optional[Callable] = None,
        metric_fn: Optional[Callable] = None,
        tokenizer=None,
        logit_mask=None,
    ):
        super().__init__(config, reward_fn, metric_fn, tokenizer, logit_mask)
        method: PPOConfig = config.method
        train = config.train

        self.mesh = make_mesh(train.mesh)
        self.rng = set_seed(train.seed)
        # grouped sampling (orchestrator repeats each chunk prompt G times);
        # scale_reward "group" whitens scores within each group. Validated
        # before any model construction — config errors should be instant.
        self.group_size = int(getattr(method, "group_size", 1) or 1)
        if method.scale_reward == "group" and self.group_size < 2:
            raise ValueError(
                'scale_reward "group" needs method.group_size >= 2 '
                f"(got {self.group_size})"
            )

        from trlx_tpu.trainer.grpo_trainer import GRPOConfig, GRPOMixin

        if isinstance(method, GRPOConfig) and not isinstance(self, GRPOMixin):
            # GRPO needs the grouped sampler expansion + advantage path;
            # running its config through plain PPO would silently train
            # classic PPO with vf_coef=0 on ungrouped rollouts
            raise ValueError(
                "method GRPOConfig requires a GRPO trainer (GRPOTrainer / "
                f"Seq2SeqGRPOTrainer); got {type(self).__name__}"
            )

        if tokenizer is None and config.model.tokenizer_path:
            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(
                config.model.tokenizer_path, local_files_only=True
            )
            if self.tokenizer.pad_token_id is None:
                self.tokenizer.pad_token = self.tokenizer.eos_token

        init_params = self._setup_model()

        # Pipeline parallelism: with a pp axis of size > 1, the PPO
        # update's full-sequence forwards (policy response_forward + frozen
        # ref) run the transformer blocks through the GPipe pipeline
        # (`models/pp_runner.py`); embed/heads and the sampler run under
        # plain GSPMD, replicated over pp.
        self.pp_stages = dict(self.mesh.shape).get("pp", 1)
        self.pp_microbatches = train.pp_microbatches
        self.pp_virtual_stages = train.pp_virtual_stages
        self.pp_remat = train.pp_remat
        if self.pp_remat and self.pp_virtual_stages > 1:
            raise NotImplementedError(
                "pp_remat runs the v=1 schedule; drop pp_virtual_stages "
                "or pp_remat (the two memory/bubble trades do not compose "
                "yet)"
            )
        if self.pp_stages > 1:
            self._validate_pp_mesh(config, train)

        gen_kwargs = dict(method.gen_kwargs)
        self.apply_tokenizer_gen_defaults(gen_kwargs)
        self._amend_gen_kwargs(gen_kwargs)
        self.gen_config = GenerationConfig.from_dict(gen_kwargs)
        # decode-budget sizing state for bind_prompt_budget: the
        # configured ceiling, and the min real prompt length of every
        # pipeline bound so far (train + eval)
        self._gen_budget_cap = self.gen_config.max_new_tokens
        self._bound_min_prompts: Dict[str, int] = {}
        self.query_length = train.seq_length
        self._check_response_budget(train)
        validate_gen_config(
            self.gen_config,
            getattr(self.model_config, "vocab_size", None),
            provided=set(gen_kwargs),
        )
        # rollout engine selection (train.rollout; docs/inference.md):
        # "continuous" drives collection through the slot-admission
        # engine (trlx_tpu/inference/engine.py) instead of the
        # fixed-batch sampler; per-row RNG keys make the two engines
        # per-row token-identical, so the fixed path stays the parity
        # baseline. Parsed before _build_jitted_fns: per_row_rng changes
        # the sampler's compiled key plumbing.
        from trlx_tpu.inference import RolloutEngineConfig

        self.rollout_config = RolloutEngineConfig.from_dict(train.rollout)
        self.rollout_engine = self.rollout_config.engine
        if self.rollout_engine == "continuous":
            self._validate_continuous_engine()
        # Asynchronous actor–learner mode (train.async_rl,
        # trainer/async_rl.py, docs/async_pipeline.md): the streamed
        # phase gains version-tagged rollouts, a bounded-staleness
        # version-lag guard, and in-flight weight pushes to the engine.
        # Parsed here (after the rollout engine) because async requires
        # the continuous engine — the actors ARE the engine.
        from trlx_tpu.trainer.async_rl import AsyncRLConfig

        self.async_config = AsyncRLConfig.from_dict(train.async_rl)
        if self.async_config.enabled:
            self._validate_async_rl()
        # actor device-subset state (async_rl.actor_fraction < 1): built
        # lazily with the engine; None = actors share the trainer mesh
        self._actor_mesh = None
        self._actor_param_shardings = None
        if self.rollout_config.rows_per_row_rng:
            import dataclasses

            self.gen_config = dataclasses.replace(
                self.gen_config, per_row_rng=True
            )
        self._rollout_engine_obj = None
        # per-row RNG phase state: one phase key (split from self.rng
        # exactly once per collect phase, lazily) + a row cursor counting
        # rows in draw order — fold_in(phase_key, draw_index) is each
        # row's base key on BOTH engines, which is what makes their
        # rollouts comparable row-by-row
        self._rollout_phase_key = None
        self._rollout_row_cursor = 0
        if train.logprob_chunk:
            if train.logprob_chunk < 0:
                raise ValueError(
                    f"train.logprob_chunk={train.logprob_chunk} must be >= 0"
                )
            if not self._supports_logprob_chunk():
                # a silently-ignored memory flag is worse than a refusal
                raise NotImplementedError(
                    f"train.logprob_chunk is not supported by "
                    f"{type(self).__name__} (causal-path feature; the "
                    f"seq2seq forward computes its own logits); remove "
                    f"the key"
                )
            if self.gen_config.max_new_tokens % train.logprob_chunk:
                raise ValueError(
                    f"train.logprob_chunk={train.logprob_chunk} must divide "
                    f"gen max_new_tokens={self.gen_config.max_new_tokens}"
                )

        # --- params, shardings, optimizer, state ---
        self.rng, init_rng = jax.random.split(self.rng)
        params = self._init_params(init_rng)
        if init_params is not None:
            params[self.backbone_key] = init_params

        self.param_shardings = self._shardings_for(params)
        params = jax.device_put(params, self.param_shardings)

        # Frozen KL reference. Two modes, as upstream (`ppo_models.py:505-558`
        # vs `ppo_orchestrator.py:41-43`):
        # - hydra (branch depth > 0): keep only the top-k blocks + ln_f +
        #   embedding as the frozen branch; the (frozen) trunk is shared
        #   with the policy — half the reference-model memory;
        # - full copy otherwise (the fork's active path for T5).
        # The branch depth is `model.ref_branch_layers` when set, else
        # `num_layers_unfrozen` — decoupled because in the reference as
        # shipped num_layers_unfrozen ONLY sizes the branch
        # (`ppo_models.py:525-536`; the freezing block is commented out)
        # while the policy trains all layers.
        # jnp.copy forces fresh buffers — the policy's are donated each step.
        self.ref_branch = config.model.resolved_ref_branch_layers
        if not 0 <= self.ref_branch <= self._n_layers():
            key = (
                "model.ref_branch_layers"
                if config.model.ref_branch_layers is not None
                # unset: the value defaulted from num_layers_unfrozen —
                # name the key the user actually wrote
                else "model.num_layers_unfrozen"
            )
            raise ValueError(
                f"{key}={self.ref_branch} must be in "
                f"[0, n_layer={self._n_layers()}]"
            )
        self.use_hydra = self.ref_branch > 0 and self._supports_hydra()
        if self.use_hydra:
            self.branch_start = self._n_layers() - self.ref_branch
            backbone = params[self.backbone_key]
            # keep top-k blocks + everything the LM head path needs (ln_f,
            # tied wte or untied lm_head); drop trunk blocks + wpe
            ref_subset = {
                k: v
                for k, v in backbone.items()
                if not k.startswith(("h_", "wpe"))
                or (k.startswith("h_") and int(k.split("_")[1]) >= self.branch_start)
            }
            self.ref_shardings = self._shardings_for(ref_subset)
            self.ref_params = jax.device_put(
                jax.tree_util.tree_map(jnp.copy, ref_subset), self.ref_shardings
            )
        else:
            self.ref_shardings = self._shardings_for(params[self.backbone_key])
            self.ref_params = jax.device_put(
                jax.tree_util.tree_map(jnp.copy, params[self.backbone_key]),
                self.ref_shardings,
            )

        trainable = unfrozen_param_mask(
            params, config.model.num_layers_unfrozen, self._n_layers()
        )
        self.trainable_mask = trainable
        self.tx = make_optimizer(train, train.total_steps, trainable)
        opt_shapes = jax.eval_shape(self.tx.init, params)
        self.opt_shardings = self._shardings_for(opt_shapes)
        opt_state = jax.jit(self.tx.init, out_shardings=self.opt_shardings)(params)

        self.state = TrainState(
            params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32)
        )
        self.state_shardings = TrainState(
            params=self.param_shardings,
            opt_state=self.opt_shardings,
            step=replicated(self.mesh),
        )

        self.buffer = PPORolloutBuffer()
        self.kl_coef = float(method.init_kl_coef)
        self.mean_kl = 0.0
        # streamed collect→train phase state (docs/async_pipeline.md):
        # while a phase is active, `_behavior_params` is the frozen
        # behavior-policy snapshot every sampler/ref forward runs on —
        # epoch-1 updates mutate `self.state` underneath without touching
        # rollout semantics.
        self._stream: Optional[_StreamedPhase] = None
        self._behavior_params = None
        self._last_overlap_stats: Dict[str, float] = {}
        self._last_phase_mean_kl = 0.0
        # phase counter + single-phase profiler window (telemetry/
        # profiler.py): _collect_phase opens phase N, the learn-loop's
        # phase epilogue closes it (train.profile_phase). A disabled
        # placeholder until learn() arms it so orchestrator-driven runs
        # outside learn (bench, A/Bs) can hit the hooks safely.
        from trlx_tpu.telemetry.profiler import PhaseProfiler

        self._phase_index = -1
        self._phase_profiler = PhaseProfiler(None, None)
        # health/flight phase id for direct drivers of the phase API
        # (bench, perf/health-smoke harnesses): learn() advances
        # _phase_index via _collect_phase; outside learn it stays -1,
        # so health_phase_id falls back to a counter bumped by
        # begin_streamed_phase
        self._health_phase = -1

        self.setup_ep_axis(self.mesh, self.family)
        # MoE families contribute router load-balancing losses to the
        # training objective (collected via the "moe_losses" sow in
        # _forward_logprobs_values)
        self._moe_family = bool(getattr(self.family, "supports_ep", False))
        self._setup_rollout_cast(train)
        self._build_jitted_fns()

    # ------------------- rollout-phase weight precision ------------------ #

    def _supports_rollout_cast(self) -> bool:
        """Causal families keep bit-identical outputs under the cast (every
        op casts params to the compute dtype per use; see TrainConfig).
        Subclasses whose models consume f32 params directly override."""
        return True

    def _setup_rollout_cast(self, train) -> None:
        """Build the jitted master->compute-dtype param cast for the rollout
        phase (`rollout_param_cast`). Decode re-reads all weights once per
        token, so f32 masters double its HBM traffic; the sampler and the
        frozen ref instead get a compute-dtype copy, refreshed once per
        collect phase. Leaves computing in f32 — value/Q-head ``fc2``, MoE
        ``router`` — stay f32 so outputs are bit-identical."""
        self._rollout_cast_jit = None
        self._rollout_params_cache = None
        self._rollout_compute_dtype = None
        cdtype = jnp.dtype(getattr(self.model_config, "dtype", train.dtype))
        # the params' ACTUAL storage dtype is the arch's param_dtype (which
        # model_arch may override independently of train.param_dtype)
        pdtype = jnp.dtype(
            getattr(self.model_config, "param_dtype", train.param_dtype)
        )
        if (
            not getattr(train, "rollout_param_cast", False)
            or not self._supports_rollout_cast()
            or cdtype == pdtype
        ):
            return

        from trlx_tpu.utils import compute_dtype_cast

        def cast_tree(params):
            return compute_dtype_cast(params, cdtype)

        self._rollout_compute_dtype = cdtype
        self._rollout_cast_jit = jax.jit(
            cast_tree,
            in_shardings=(self.param_shardings,),
            out_shardings=self.param_shardings,
        )
        # the frozen ref is inference-only: cast once, permanently (also
        # halves its resident memory)
        self.ref_params = jax.jit(
            cast_tree,
            in_shardings=(self.ref_shardings,),
            out_shardings=self.ref_shardings,
        )(self.ref_params)

    def rollout_params(self):
        """Params the rollout phase runs on.

        While a streamed phase is active: the frozen behavior snapshot
        taken at :meth:`begin_streamed_phase` — NOT the live masters,
        which epoch-1 updates are mutating (and donating) underneath.
        Otherwise: the compute-dtype copy when the cast is enabled (recast
        lazily after each train phase — TrainState is replaced on update,
        so object identity detects staleness), else the f32 masters."""
        if self._behavior_params is not None:
            return self._behavior_params
        if self._rollout_cast_jit is None:
            return self.state.params
        master = self.state.params
        cache = self._rollout_params_cache
        if cache is None or cache[0] is not master:
            self._rollout_params_cache = (master, self._rollout_cast_jit(master))
        return self._rollout_params_cache[1]

    # ----------------------- model-family hooks ----------------------- #

    def _setup_model(self):
        """Build arch config + flax modules; return converted checkpoint
        params (or None)."""
        self.family, self.model_config, init_params = get_causal_arch(self.config)
        self.model = CausalLMWithValueHead(
            self.model_config, backbone_cls=self.family.backbone_cls
        )
        self.backbone = self.family.backbone_cls(self.model_config)
        self.partition_rules = self.family.partition_rules
        return init_params

    def _amend_gen_kwargs(self, gen_kwargs: Dict) -> None:
        pass

    def _validate_pp_mesh(self, config, train) -> None:
        """Family/shape checks for a pp axis > 1 (overridable per trainer:
        the seq2seq variant validates both T5 stacks instead)."""
        from trlx_tpu.models.pp_runner import supports_pp

        if not supports_pp(self.model_config):
            raise NotImplementedError(
                f"pp mesh axis is integrated for the causal families "
                f"(gpt2/gptj/gpt_neo/gpt_neox) but not "
                f"{type(self.model_config).__name__}: MoE layers have "
                f"non-uniform per-layer params (no stage stacking); "
                f"use dp/fsdp/tp/sp/ep instead"
            )
        L = self._n_layers()
        if L % self.pp_stages:
            raise ValueError(
                f"n_layer={L} must divide into pp={self.pp_stages} stages"
            )
        if config.model.resolved_ref_branch_layers > 0:
            # hydra under pp needs the branch point on a stage boundary
            # (the capture is a stage's input — round 3; previously
            # refused outright)
            chunk = L // self.pp_stages
            branch = L - config.model.resolved_ref_branch_layers
            if branch % chunk:
                raise NotImplementedError(
                    f"hydra under pp needs the branch point on a stage "
                    f"boundary: L={L}, pp={self.pp_stages} gives stage "
                    f"size {chunk}, but L - ref_branch_layers = "
                    f"{branch}; adjust num_layers_unfrozen / "
                    f"ref_branch_layers or use the full-copy reference"
                )
            if train.pp_virtual_stages > 1:
                raise NotImplementedError(
                    "hydra under pp runs the v=1 schedule (the branch "
                    "capture is a single stage's input, which the "
                    "interleaved schedule does not expose); drop "
                    "pp_virtual_stages or use the full-copy reference"
                )

    def _check_response_budget(self, train) -> None:
        """Every rollout must have >= 1 response token by construction: a
        zero-length response's terminal score lands on a masked slot and
        GAE (`ops/ppo_math.py` rewards*mask) silently zeroes it. For causal
        LMs, gen max_length caps prompt + generated — but whether a prompt
        can fill that budget depends on *real* (non-pad) prompt lengths,
        which only the pipeline knows (train.seq_length is just the padded
        width; the reference's own `configs/ppo_config.yml` pairs
        max_length 49 with seq_length 512 and is valid because its prompts
        are short). The exact check runs in :meth:`bind_prompt_budget`
        when the orchestrator attaches the training pipeline."""

    def bind_prompt_budget(self, pipeline, role: str = "train") -> None:
        """Validate + bound the decode budget against a bound pipeline's
        real prompt lengths (causal: ``max_length`` caps prompt +
        generated).

        - ``role="train"``: raises when some prompt already fills
          ``max_length`` — its rollout would have zero response tokens,
          whose terminal score lands on a masked slot and GAE silently
          drops it. For ``role="eval"`` the same situation only warns
          (an empty eval generation is scored as an empty string, not
          a corrupted update).
        - Sizes ``max_new_tokens`` to the largest per-row budget over
          *all* bound pipelines (``max_length`` − shortest real prompt
          anywhere) when the config over-allocated (reference configs
          write HF's ``max_length``; ``GenerationConfig.from_dict`` maps
          it to the decode budget) — the compiled decode then scans
          fewer steps and sizes a smaller KV cache, without capping a
          later-bound short-prompt eval pipeline below its entitlement.
          Rebuilds the jitted sampler on change.
        """
        max_len = self.gen_config.max_length
        longest = getattr(pipeline, "max_prompt_tokens", None)
        if max_len <= 0 or longest is None or not len(pipeline):
            return
        if longest >= max_len:
            msg = (
                f"a prompt with {longest} real tokens fills gen_kwargs "
                f"max_length={max_len} (prompt + generated), leaving "
                "zero response tokens; raise max_length, shorten the "
                "prompts, or use max_new_tokens"
            )
            if role == "train":
                raise ValueError(
                    msg + " (a zero-length rollout's terminal reward is "
                    "silently dropped by PPO)"
                )
            import warnings

            warnings.warn(msg + " (eval will score an empty string)")
        # keyed by role so a *replaced* pipeline overrides (not
        # min-accumulates) its predecessor's entitlement — the budget can
        # re-shrink when a short-prompt eval pipeline is swapped out
        self._bound_min_prompts[role] = int(pipeline.min_prompt_tokens)
        budget = max_len - min(self._bound_min_prompts.values())
        new = min(self._gen_budget_cap, budget) if budget > 0 else (
            self._gen_budget_cap
        )
        if new != self.gen_config.max_new_tokens:
            import dataclasses

            self.gen_config = dataclasses.replace(
                self.gen_config, max_new_tokens=new
            )
            self._rebuild_sampler()

    def add_eval_pipeline(self, pipeline) -> None:
        super().add_eval_pipeline(pipeline)
        self.bind_prompt_budget(pipeline, role="eval")

    def _n_layers(self) -> int:
        from trlx_tpu.models.registry import num_layers_of

        return num_layers_of(self.model_config)

    def _init_params(self, rng):
        dummy = jnp.zeros((1, 8), jnp.int32)
        return self.model.init(rng, dummy)["params"]

    def _make_sampler(self) -> Callable:
        """Jittable (params, prompt_ids, prompt_mask, rng) -> SampleOutput.

        Under a pp mesh the rollout runs the pipelined cached forward with
        STAGE-RESIDENT KV buffers (`models/pp_runner.py`): each pp device
        holds only its stage's layers + cache during the dominant phase,
        instead of a full replicated copy."""
        if self.pp_stages > 1:
            from trlx_tpu.models.pp_runner import (
                make_pp_sampler_apply,
                pp_decode_kit,
                pp_stack_sampler_params,
            )

            init_cache_fn, cache_sharding = pp_decode_kit(
                self.model_config, self.mesh
            )
            inner = make_sampler(
                make_pp_sampler_apply(
                    self.model_config, self.mesh, self.pp_microbatches
                ),
                init_cache_fn,
                self.gen_config,
                self.query_length,
                with_values=True,
                cache_sharding=cache_sharding,
            )

            def sampler(params, prompt_ids, prompt_mask, rng):
                # stack/reshard the trunk blocks ONCE per invocation, not
                # once per decoded token inside the sampler's scan
                packed = pp_stack_sampler_params(
                    self.model_config, self.mesh, params
                )
                return inner(packed, prompt_ids, prompt_mask, rng)

            return sampler

        def apply_fn(params, input_ids, attention_mask=None, position_ids=None,
                     cache=None, cache_index=None, last_only=False):
            return self.model.apply(
                {"params": params},
                input_ids,
                attention_mask=attention_mask,
                position_ids=position_ids,
                cache=cache,
                cache_index=cache_index,
                last_only=last_only,
            )

        return make_sampler(
            apply_fn,
            functools.partial(self.family.init_cache, self.model_config),
            self.gen_config,
            self.query_length,
            with_values=True,
            cache_sharding=self._decode_cache_sharding(),
        )

    def _forward_logprobs_values(self, params, mb: PPORolloutBatch):
        """Policy forward -> (logprobs, values, entropy?, moe_losses?) over
        response positions.

        Causal LM: forward [query; response]; hidden states are sliced to
        positions Q-1..Q+R-2 (the states that *predict* each response token)
        *before* the LM/value heads run (``response_forward``). Per-position
        entropy is computed only when the entropy bonus is on. For MoE
        families the forward opens the ``moe_losses`` sow collection and
        returns the aggregated router regularizers (Switch aux + z-loss +
        load diagnostic) for the training loss."""
        Q = self.query_length
        full_ids = jnp.concatenate([mb.query_tokens, mb.response_tokens], axis=1)
        full_mask = jnp.concatenate([mb.query_mask, mb.response_mask], axis=1)
        moe = None
        if self.pp_stages > 1:
            from trlx_tpu.models.pp_runner import pp_response_forward

            logits, values = pp_response_forward(
                self.model_config, params, full_ids, full_mask, Q,
                self.mesh, self.pp_microbatches,
                virtual_stages=self.pp_virtual_stages,
                remat=self.pp_remat,
            )
        elif self._moe_family:
            from trlx_tpu.models.gpt2_moe import moe_loss_summary

            (logits, values), state = self.model.apply(
                {"params": params}, full_ids, full_mask, Q,
                method=self.model.response_forward, mutable=["moe_losses"],
            )
            moe = moe_loss_summary(state["moe_losses"])
        elif self._logprob_chunk_active():
            # chunked logprob/CE (train.logprob_chunk): head + log-softmax
            # + gather per chunk under jax.checkpoint — the full [B, R, V]
            # f32 logits buffer never materializes; bwd recomputes each
            # chunk's logits from its saved hidden slice
            hidden, values = self.model.apply(
                {"params": params}, full_ids, full_mask, Q,
                method=self.model.response_hidden,
            )
            c = self.config.train.logprob_chunk
            B, R, d = hidden.shape
            if R % c:
                raise ValueError(
                    f"train.logprob_chunk={c} does not divide the bound "
                    f"response width {R} (bind_prompt_budget shrank the "
                    f"decode budget); pick a chunk dividing both"
                )
            n = R // c
            hs = hidden.reshape(B, n, c, d).swapaxes(0, 1)  # [n, B, c, d]
            toks = mb.response_tokens.reshape(B, n, c).swapaxes(0, 1)
            backbone_params = params[self.backbone_key]

            @jax.checkpoint
            def chunk_logprobs(h_c, t_c):
                logits_c = self.backbone.apply(
                    {"params": backbone_params}, h_c,
                    method=self.backbone.logits,
                )
                return logprobs_from_logits(
                    logits_c.astype(jnp.float32), t_c
                )

            def body(carry, xs):
                h_c, t_c = xs
                return carry, chunk_logprobs(h_c, t_c)

            _, lps = jax.lax.scan(body, None, (hs, toks))
            logprobs = lps.swapaxes(0, 1).reshape(B, R)
            return logprobs, values.astype(jnp.float32), None, moe
        else:
            logits, values = self.model.apply(
                {"params": params}, full_ids, full_mask, Q,
                method=self.model.response_forward,
            )
        logprobs = logprobs_from_logits(logits, mb.response_tokens)
        # entropy also under health (train.health.enabled) at ent_coef=0:
        # the entropy-collapse detector needs the series; the loss only
        # consumes it when the bonus coefficient is nonzero
        entropy = (
            _policy_entropy(logits)
            if (self.config.method.ent_coef or self._health_enabled)
            else None
        )
        return logprobs, values.astype(jnp.float32), entropy, moe

    def _supports_logprob_chunk(self) -> bool:
        """Whether this trainer class can honor ``train.logprob_chunk``
        at all (the seq2seq trainer overrides its forward and returns
        False — the flag refuses loudly there instead of no-opping)."""
        return True

    def _logprob_chunk_active(self) -> bool:
        """Chunked logprobs apply on the plain causal path only: pp has
        its own response forward, MoE threads the sow collection through
        response_forward, and the entropy bonus needs full-vocab terms."""
        c = self.config.train.logprob_chunk
        return bool(c) and not (
            self.pp_stages > 1
            or self._moe_family
            or self.config.method.ent_coef
        )

    def _supports_hydra(self) -> bool:
        return True

    def _ref_logprobs(self, ref_params, policy_params, q_ids, q_mask, r_ids, r_mask):
        """KL-reference logprobs of the sampled responses.

        Hydra mode re-runs only the frozen-copy top blocks from the shared
        trunk's activation (`ppo_models.py:541-558`); ``policy_params``
        provide the trunk. Whether that trunk is stationary depends on the
        freezing config: with ``num_layers_unfrozen > 0`` the trunk layers
        are frozen and the reference is fixed; with the decoupled faithful
        config (``num_layers_unfrozen: 0`` + ``ref_branch_layers``) the
        trunk TRAINS, so the hydra reference drifts with the policy —
        exactly as the reference-as-shipped behaves (its
        ``forward_hydra`` reads the live trunk while only the branch
        copies are frozen). Do not cache these logprobs across updates."""
        Q = self.query_length
        full_ids = jnp.concatenate([q_ids, r_ids], axis=1)
        full_mask = jnp.concatenate([q_mask, r_mask], axis=1)
        if self.pp_stages > 1:
            if self.use_hydra:
                from trlx_tpu.models.pp_runner import pp_hydra_ref_logits

                logits = pp_hydra_ref_logits(
                    self.model_config, policy_params[self.backbone_key],
                    ref_params, full_ids, full_mask, Q, self.branch_start,
                    self.mesh, self.pp_microbatches,
                )
                return logprobs_from_logits(logits, r_ids)
            from trlx_tpu.models.pp_runner import pp_ref_logits

            logits = pp_ref_logits(
                self.model_config, ref_params, full_ids, full_mask, Q,
                self.mesh, self.pp_microbatches,
                virtual_stages=self.pp_virtual_stages,
            )
            return logprobs_from_logits(logits, r_ids)
        if self.use_hydra:
            trunk_out = self.backbone.apply(
                {"params": policy_params[self.backbone_key]},
                full_ids,
                attention_mask=full_mask,
                capture_hidden_at=self.branch_start,
                compute_logits=False,  # only the captured hidden is used
            )
            out = self.backbone.apply(
                {"params": ref_params},
                full_ids,
                attention_mask=full_mask,
                start_layer=self.branch_start,
                hidden_override=trunk_out["branch_hidden"],
                compute_logits=False,
            )
        else:
            out = self.backbone.apply(
                {"params": ref_params}, full_ids, attention_mask=full_mask,
                compute_logits=False,
            )
        # LM head only on response-predicting positions
        logits = self.backbone.apply(
            {"params": ref_params}, out["hidden"][:, Q - 1 : -1],
            method=self.backbone.logits,
        )
        return logprobs_from_logits(logits, r_ids)

    # ------------------------------------------------------------------ #

    def _shape_rewards(self, logprobs, ref_logprobs, response_mask, scores, kl_coef):
        """Per-token shaped rewards: −kl_coef·KL with the terminal score at
        the last valid slot (reference `ppo_orchestrator.py:163-167`).
        Jitted by ``_build_jitted_fns``; subclasses may post-process (GRPO
        stores group-normalized advantages here instead)."""
        maskf = response_mask.astype(jnp.float32)
        kl_per_token = (logprobs - ref_logprobs) * maskf
        rewards = -kl_coef * kl_per_token
        last = jnp.clip(jnp.sum(response_mask, axis=1) - 1, 0, None)
        rewards = rewards.at[jnp.arange(rewards.shape[0]), last].add(scores)
        mean_kl = jnp.mean(jnp.sum(kl_per_token, axis=1))
        return rewards, mean_kl

    def _advantages_and_returns(self, mb: PPORolloutBatch):
        """(advantages, returns) for the PPO loss — GAE over the stored
        values/rewards by default; traced inside the jitted train step."""
        method: PPOConfig = self.config.method
        return get_advantages_and_returns(
            mb.values, mb.rewards, mb.response_mask, method.gamma, method.lam
        )

    def _shardings_for(self, tree):
        specs = make_partition_specs(tree, self.mesh, self.partition_rules)
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s),
            specs,
            is_leaf=lambda x: isinstance(x, P),
        )

    def _rebuild_sampler(self):
        """(Re)jit the rollout sampler from the current ``gen_config`` —
        called at construction and again by :meth:`bind_prompt_budget`
        when the decode budget shrinks (jit is lazy; no compile happens
        until the first rollout, so a rebuild before training is free)."""
        batch_sh = batch_sharding(self.mesh)
        rep = replicated(self.mesh)
        self._sample_jit = jax.jit(
            self._make_sampler(),
            in_shardings=(self.param_shardings, batch_sh, batch_sh, rep),
            out_shardings=batch_sh,
        )
        # a changed decode budget resizes the engine's KV capacity and
        # output buffers — rebuild it lazily from the new gen_config
        self._rollout_engine_obj = None

    def _build_jitted_fns(self):
        method: PPOConfig = self.config.method
        batch_sh = batch_sharding(self.mesh)
        rep = replicated(self.mesh)
        self._batch_sh = batch_sh

        self._rebuild_sampler()

        # Behavior-policy snapshot for the streamed phase: the compute-dtype
        # cast (when enabled) plus an unconditional per-leaf copy. The copy
        # matters: pjit forwards pass-through inputs to outputs, so a leaf
        # the cast leaves untouched (ROLLOUT_CAST_EXCLUDE, or every leaf in
        # the no-cast path) would ALIAS the master buffer — which the very
        # first streamed train step donates. The snapshot must own every
        # buffer it serves to in-flight samplers.
        cast_active = self._rollout_cast_jit is not None
        snap_dtype = self._rollout_compute_dtype

        def behavior_snapshot(params):
            if cast_active:
                from trlx_tpu.utils import compute_dtype_cast

                params = compute_dtype_cast(params, snap_dtype)
            return jax.tree_util.tree_map(jnp.copy, params)

        self._behavior_snapshot_jit = jax.jit(
            behavior_snapshot,
            in_shardings=(self.param_shardings,),
            out_shardings=self.param_shardings,
        )
        # Async actor–learner weight push (trainer/async_rl.py): the
        # refreshed behavior policy actors receive MID-generation. Same
        # math as the phase-start snapshot — compute-dtype cast (when
        # enabled) + unconditional per-leaf copy, and the copy is just
        # as load-bearing here: the pushed tree must own every buffer it
        # hands the engine, because the very next train step donates the
        # masters it would otherwise alias. A separate jit instance so
        # the analysis harness audits the push program the async path
        # actually dispatches (subject ppo.async_weight_push).
        self._weight_push_jit = jax.jit(
            behavior_snapshot,
            in_shardings=(self.param_shardings,),
            out_shardings=self.param_shardings,
        )

        self._score_ref_jit = jax.jit(
            self._ref_logprobs,
            in_shardings=(
                self.ref_shardings,
                self.param_shardings,
                batch_sh,
                batch_sh,
                batch_sh,
                batch_sh,
            ),
            out_shardings=batch_sh,
        )

        self._compute_rewards_jit = jax.jit(
            self._shape_rewards,
            in_shardings=(batch_sh, batch_sh, batch_sh, batch_sh, rep),
            out_shardings=(batch_sh, rep),
        )

        def train_step_with_adv(
            state: TrainState, mb: PPORolloutBatch, advantages, returns
        ):
            def loss_fn(params):
                # stop_gradient on frozen leaves: XLA prunes the backward
                # below the branch point (real work-avoidance when
                # num_layers_unfrozen > 0 re-enables the reference's
                # commented-out freezing)
                params = stop_frozen_gradients(params, self.trainable_mask)
                logprobs, values, entropy, moe = self._forward_logprobs_values(
                    params, mb
                )
                loss, stats = ppo_loss(
                    logprobs,
                    values,
                    mb.logprobs,
                    mb.values,
                    advantages,
                    returns,
                    mb.response_mask,
                    method.cliprange,
                    method.cliprange_value,
                    method.vf_coef,
                    ent_coef=method.ent_coef,
                    entropy=entropy,
                    health=self._health_enabled,
                    health_ev=self._health_ev,
                )
                if moe is not None:
                    # Switch load-balancing: without this, top-1 routing
                    # collapses onto few experts once capacity drops are
                    # real (anything below capacity_factor >= n_experts)
                    from trlx_tpu.models.gpt2_moe import apply_router_penalty

                    loss, stats = apply_router_penalty(
                        loss, stats, moe, self.model_config
                    )
                return loss, stats

            (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params
            )
            updates, new_opt_state = self.tx.update(
                grads, state.opt_state, state.params
            )
            new_params = optax.apply_updates(state.params, updates)
            stats["optimizer/grad_norm"] = optax.global_norm(grads)
            if self._health_enabled:
                # shaped-return distribution next to the loss stats — a
                # pure extra output riding the same transfer, so the
                # one-transfer-per-update discipline holds (pinned in
                # tests/test_health.py)
                stats.update(
                    reward_health_stats(mb.rewards, mb.response_mask)
                )
            new_state = TrainState(
                params=new_params, opt_state=new_opt_state, step=state.step + 1
            )
            return new_state, stats

        def train_step(state: TrainState, mb: PPORolloutBatch):
            advantages, returns = self._advantages_and_returns(mb)
            return train_step_with_adv(state, mb, advantages, returns)

        self._train_step_jit = jax.jit(
            train_step,
            in_shardings=(self.state_shardings, batch_sh),
            out_shardings=(self.state_shardings, rep),
            donate_argnums=(0,),
        )

        def train_phase(state: TrainState, mbs: PPORolloutBatch):
            """One full buffer pass in a single dispatch: flat scan over
            [n_mb * ppo_epochs] pre-repeated minibatch slices (the reference
            inner loop, `accelerate_base_model.py:253-266`, realized as
            consecutive identical slices) — one train-step body to compile.

            GAE/whitening is params-INDEPENDENT, so it is hoisted out of
            the scan and computed for every minibatch in one batched pass:
            inside the scan it was a fresh R-step sequential chain per
            update — measured ~5 ms each (latency-, not compute-bound;
            bench_train_audit.py) — i.e. ~29% of the faithful workload's
            17 ms train step. vmap turns the 32 sequential chains into one
            chain of batched steps; per-minibatch whitening semantics are
            bitwise preserved (vmap axis = the minibatch axis the stats
            were already computed within)."""
            advantages, returns = jax.vmap(self._advantages_and_returns)(mbs)

            def step(st, xs):
                mb, adv, ret = xs
                return train_step_with_adv(st, mb, adv, ret)

            return jax.lax.scan(step, state, (mbs, advantages, returns))

        from trlx_tpu.parallel.mesh import stacked_batch_sharding

        self._stacked_batch_sh = stacked_batch_sharding(self.mesh)
        self._train_phase_jit = jax.jit(
            train_phase,
            in_shardings=(self.state_shardings, self._stacked_batch_sh),
            out_shardings=(self.state_shardings, rep),
            donate_argnums=(0,),
        )

    # --------------------- rollout engine (continuous) ----------------- #

    def _supports_continuous_engine(self) -> bool:
        """Causal-LM trainers share the engine's apply/cache contract;
        the seq2seq trainer (encoder/decoder split, cross-KV) overrides
        to refuse loudly instead of silently running the fixed path."""
        return True

    def _validate_continuous_engine(self) -> None:
        if not self._supports_continuous_engine():
            raise NotImplementedError(
                f"train.rollout engine 'continuous' is not supported by "
                f"{type(self).__name__} (causal-LM decode path); use "
                "engine: fixed"
            )
        if self.pp_stages > 1:
            raise NotImplementedError(
                "train.rollout engine 'continuous' does not compose with "
                "a pp mesh axis yet (the engine decodes under plain "
                "GSPMD; pp decode uses stage-resident KV buffers); use "
                "engine: fixed or drop the pp axis"
            )
        if self.group_size > 1:
            raise NotImplementedError(
                "train.rollout engine 'continuous' does not support "
                "grouped sampling (method.group_size > 1 / GRPO) yet: "
                "harvest groups complete in finish order, breaking the "
                "group-contiguity the grouped reward shaping assumes; "
                "use engine: fixed"
            )

    def _validate_async_rl(self) -> None:
        """``train.async_rl.enabled`` preconditions, checked at
        construction so config errors are instant: the actors ARE the
        continuous engine (whose own validation already refuses pp
        meshes, grouped/GRPO sampling, and seq2seq)."""
        if self.rollout_engine != "continuous":
            raise ValueError(
                "train.async_rl.enabled requires train.rollout.engine: "
                "'continuous' — the asynchronous actors run the "
                "slot-admission engine (docs/async_pipeline.md); add "
                "rollout: {engine: continuous} or disable async_rl"
            )
        if not self.config.train.phase_overlap:
            # the landing hook is the learner's whole consumption path;
            # with overlap globally off the run would be silently serial
            # while the user believes async is on — refuse loudly, like
            # every other invalid async combination
            raise ValueError(
                "train.async_rl.enabled requires train.phase_overlap: "
                "true (the streamed landing hook is how the async "
                "learner consumes rollouts); drop phase_overlap: false "
                "or disable async_rl"
            )

    def _to_actor(self, params):
        """Reshard a learner-mesh param tree onto the actor device
        subset (identity when actors share the trainer mesh). This is
        the learner→actor transfer of the disaggregated layout — on
        multi-host it becomes the ICI weight broadcast."""
        if self._actor_param_shardings is None:
            return params
        return jax.device_put(params, self._actor_param_shardings)

    def engine_start_params(self):
        """Params the engine's phase starts on: the behavior snapshot
        (or cast masters), resharded to the actor subset when one is
        configured."""
        return self._to_actor(self.rollout_params())

    def reset_rollout_phase(self) -> None:
        """Start a fresh rollout phase for per-row RNG: the next sampler
        or engine call derives a new phase key (ONE split of self.rng,
        identical across engines) and row indices restart at 0."""
        self._rollout_phase_key = None
        self._rollout_row_cursor = 0

    def rollout_phase_key(self):
        """The phase's per-row RNG base key (lazily split once)."""
        if self._rollout_phase_key is None:
            self.rng, self._rollout_phase_key = jax.random.split(self.rng)
        return self._rollout_phase_key

    def take_row_keys(self, n: int):
        """[n, 2] per-row keys for the next ``n`` drawn rows (advances
        the draw cursor) — the fixed sampler's per-row-RNG rng argument."""
        from trlx_tpu.ops.sampling import make_row_keys

        start = self._rollout_row_cursor
        self._rollout_row_cursor += n
        return make_row_keys(
            self.rollout_phase_key(), np.arange(start, start + n)
        )

    @property
    def rollout_engine_obj(self):
        """The continuous-batching engine, built on first use (after
        bind_prompt_budget has settled the decode budget)."""
        if self._rollout_engine_obj is None:
            self._rollout_engine_obj = self._build_rollout_engine()
        return self._rollout_engine_obj

    def _build_rollout_engine(self):
        from trlx_tpu.inference.engine import ContinuousBatchingEngine

        cfg = self.rollout_config
        chunk = int(
            getattr(self.config.method, "chunk_size", 0)
            or self.config.train.batch_size
        )
        num_slots = cfg.slots or chunk

        def apply_fn(params, input_ids, attention_mask=None,
                     position_ids=None, cache=None, cache_index=None,
                     last_only=False, skip_heads=False):
            return self.model.apply(
                {"params": params},
                input_ids,
                attention_mask=attention_mask,
                position_ids=position_ids,
                cache=cache,
                cache_index=cache_index,
                last_only=last_only,
                skip_heads=skip_heads,
            )

        # actor device subset (async_rl.actor_fraction < 1): the engine
        # lives on its own dp-only submesh; params reshard to it on
        # every weight push and harvest groups reshard back at landing —
        # the single-process rehearsal of multi-host actor/learner
        # placement (ROADMAP direction 3). cache sp-sharding does not
        # apply on the dp-only actor mesh.
        engine_mesh = self.mesh
        engine_shardings = self.param_shardings
        cache_sharding = self._decode_cache_sharding()
        admit_width = cfg.admit_width
        harvest_width = cfg.harvest_width
        if self.async_config.enabled and self.async_config.actor_fraction < 1:
            from trlx_tpu.trainer.async_rl import actor_submesh

            amesh = actor_submesh(self.mesh, self.async_config.actor_fraction)
            if amesh is not None:
                specs = make_partition_specs(
                    self.state.params, amesh, self.partition_rules
                )
                ashardings = jax.tree_util.tree_map(
                    lambda s: NamedSharding(amesh, s),
                    specs,
                    is_leaf=lambda x: isinstance(x, P),
                )
                self._actor_mesh = amesh
                self._actor_param_shardings = ashardings
                engine_mesh, engine_shardings = amesh, ashardings
                cache_sharding = None
                # harvest groups cross from the actor submesh to the
                # LEARNER mesh at landing (score_ref/rewards/store all
                # run there), so the admit/harvest widths must divide
                # over BOTH meshes' data shards — round them up to the
                # lcm here (the engine itself only knows its own mesh)
                import math

                shape = dict(self.mesh.shape)
                lshard = shape.get("dp", 1) * shape.get("fsdp", 1)
                ashape = dict(amesh.shape)
                ashard = ashape.get("dp", 1) * ashape.get("fsdp", 1)
                mult = math.lcm(lshard, ashard)

                def up(n: int) -> int:
                    return ((n + mult - 1) // mult) * mult

                admit_width = up(admit_width or max(1, num_slots // 4))
                harvest_width = up(harvest_width or admit_width)
                if harvest_width > num_slots:
                    raise ValueError(
                        f"async actor/learner meshes need harvest "
                        f"groups of a multiple of {mult} rows, but "
                        f"{harvest_width} exceeds the {num_slots}-slot "
                        "pool; raise rollout.slots or actor_fraction"
                    )

        spec = cfg.spec_decode
        spec_on = spec is not None and spec.enabled
        return ContinuousBatchingEngine(
            apply_fn=apply_fn,
            init_cache_fn=functools.partial(
                self.family.init_cache, self.model_config
            ),
            gen_config=self.gen_config,
            query_length=self.query_length,
            vocab_size=self.model_config.vocab_size,
            num_slots=num_slots,
            admit_width=admit_width,
            harvest_width=harvest_width,
            block_size=cfg.block_size,
            done_poll_interval=cfg.poll_interval,
            mesh=engine_mesh,
            param_shardings=engine_shardings,
            cache_sharding=cache_sharding,
            with_values=True,
            prefill_chunk=cfg.prefill_chunk,
            prefill_chunks_per_pump=cfg.prefill_chunks_per_pump,
            # the trainer path has no prefix pool, so rollout
            # spec_decode.drafter: trie degrades to the per-row n-gram
            # fallback (TrieDrafter with pool=None behaves identically)
            spec_max_draft=spec.max_draft if spec_on else 0,
            spec_min_accept_ewma=(
                spec.min_accept_ewma if spec_on else 0.0
            ),
        )

    # ------------------------------------------------------------------ #

    def sample(self, prompt_ids, prompt_mask) -> SampleOutput:
        """Run the compiled rollout sampler on a prompt batch."""
        if self.gen_config.per_row_rng:
            key = self.take_row_keys(prompt_ids.shape[0])
        else:
            self.rng, key = jax.random.split(self.rng)
        return self._sample_jit(
            self.rollout_params(), prompt_ids, prompt_mask, key
        )

    def score_ref(self, q_ids, q_mask, r_ids, r_mask):
        # policy params only feed the hydra trunk here (the CURRENT
        # trunk, trained or frozen per config — see _ref_logprobs) —
        # the compute-dtype copy is exact for it, and halves the read
        return self._score_ref_jit(
            self.ref_params, self.rollout_params(), q_ids, q_mask, r_ids, r_mask
        )

    def compute_rewards(self, logprobs, ref_logprobs, response_mask, scores):
        rewards, mean_kl = self._compute_rewards_jit(
            logprobs,
            ref_logprobs,
            response_mask,
            jnp.asarray(scores, jnp.float32),
            jnp.asarray(self.kl_coef, jnp.float32),
        )
        # Keep the rollout KL as a device scalar: pulling it to host here
        # would cost a full transfer round-trip per chunk (~100ms on a
        # tunneled chip). Consumers (KL controller, stats logging) operate
        # on it lazily; Logger.log batches the eventual fetch.
        self.mean_kl = mean_kl
        return rewards

    def train_on_buffer(
        self, seed: int = 0, n_minibatches: Optional[int] = None
    ) -> Tuple[int, Dict[str, Any], List[float]]:
        """One fused buffer pass: every minibatch x ``ppo_epochs`` update in a
        single device dispatch (vs one dispatch per update). Returns
        ``(n_steps_taken, stacked_stats, kl_seq)``: each stats leaf has a
        leading [n_minibatches * ppo_epochs] dim (one row per update in
        execution order); ``kl_seq[k]`` is the KL coefficient after
        minibatch k (``kl_seq[0]`` = value on entry).

        The adaptive KL coefficient is advanced once per minibatch with the
        same compounding as the stepwise path (`accelerate_ppo_model.py:
        136-137`) — it only feeds the *next* experience collection, so
        updating it after the fused pass is exact.
        """
        train = self.config.train
        method: PPOConfig = self.config.method
        # n_minibatches (optional) fixes the pass size — learn() passes
        # its planned per-pass count so a buffer over-collected by a
        # non-dividing final chunk cannot train more updates than the
        # step accounting (iter_count / total_steps) assumes
        mbs = self.buffer.stacked_minibatches(
            train.batch_size, shuffle=True, seed=seed,
            sharding=self._stacked_batch_sh, repeat=method.ppo_epochs,
            n_minibatches=n_minibatches,
        )
        n_mb = len(self.buffer) // train.batch_size
        if n_minibatches is not None:
            n_mb = min(n_mb, n_minibatches)
        # the compute-dtype rollout copy is dead weight through the train
        # phase (the memory high-water mark); free it before dispatch —
        # it is recast from the new masters at the next collect anyway
        self._rollout_params_cache = None
        self.state, stats = self._train_phase_jit(self.state, mbs)
        kl_seq = [self.kl_coef]
        for _ in range(n_mb):
            kl_seq.append(
                kl_controller_update(
                    method, kl_seq[-1], self.mean_kl, train.batch_size
                )
            )
        self.kl_coef = kl_seq[-1]
        return n_mb * method.ppo_epochs, stats, kl_seq

    # ------------------ streamed collect→train phase ------------------ #
    #
    # The phase barrier between `make_experience` and the buffer pass is
    # broken while preserving EXACT on-policy semantics
    # (docs/async_pipeline.md):
    #
    # 1. `begin_streamed_phase` snapshots the behavior policy once (fresh
    #    buffers; donation-safe) and fixes the entire update schedule up
    #    front (`StreamPlan`) from the known rollout total;
    # 2. the orchestrator calls `on_rollouts_landed` after each chunk
    #    lands in the streaming buffer; epoch-1 minibatch updates are
    #    dispatched the moment their constituent rollouts exist — while
    #    later chunks are still decoding against the frozen snapshot;
    # 3. `finish_streamed_phase` dispatches any remainder, runs epochs
    #    2..ppo_epochs as the fused train_phase scan, advances the KL
    #    controller once per minibatch (it only feeds the NEXT phase),
    #    and reports overlap attribution stats.
    #
    # Every rollout samples from the same frozen snapshot and behavior
    # logprobs are recorded at decode time, so the overlapped schedule is
    # semantically identical to running the same plan serially — pinned
    # bitwise in tests/test_phase_overlap.py.

    @property
    def health_phase_id(self) -> int:
        """Phase id health events and flight records are stamped with:
        learn()'s phase counter when it is driving, else the
        begin_streamed_phase fallback counter (direct drivers) — one
        id per phase across the collect window and the epilogue."""
        return (
            self._phase_index if self._phase_index >= 0
            else self._health_phase
        )

    def begin_streamed_phase(
        self,
        seed: int = 0,
        num_rollouts: Optional[int] = None,
        overlap: Optional[bool] = None,
    ) -> "_StreamedPhase":
        """Open a streamed phase: snapshot the behavior policy, fix the
        minibatch plan, and switch the buffer to incremental stream mode.
        ``overlap=False`` runs the identical schedule serially (every
        update dispatched in :meth:`finish_streamed_phase`) — the parity
        baseline."""
        if self._stream is not None:
            raise RuntimeError(
                "a streamed phase is already active; finish_streamed_phase "
                "(or abort_streamed_phase after an error) before beginning "
                "another"
            )
        method: PPOConfig = self.config.method
        train = self.config.train
        total = int(num_rollouts if num_rollouts is not None
                    else method.num_rollouts)
        plan = make_stream_plan(
            total, train.batch_size, method.ppo_epochs, seed
        )
        if len(self.buffer):
            self.buffer.clear_history()
        self.buffer.begin_stream(plan.total)
        # direct drivers (bench, harnesses) never advance _phase_index;
        # bump the fallback health-phase id HERE so collect-window
        # events and the phase's flight record agree on the id
        self._health_phase += 1
        # the legacy lazy cast copy is dead weight once the snapshot exists
        self._rollout_params_cache = None
        # recorded so error recovery (the engine-fallback path in the
        # orchestrator) can re-begin THIS phase with the same plan seed
        self._last_stream_seed = seed
        # fresh per-row RNG phase: both rollout engines derive row keys
        # from the same single split, so a phase collected continuously
        # is row-comparable to the same phase collected fixed-batch
        self.reset_rollout_phase()
        self._behavior_params = self._behavior_snapshot_jit(self.state.params)
        # async actor–learner mode rides the streamed-phase machinery
        # with version/guard/push state on top (trainer/async_rl.py);
        # the explicit overlap=False escape (the serial parity baseline)
        # still runs the plain serial schedule even under async config
        phase_cls = (
            _AsyncStreamedPhase
            if self.async_config.enabled and overlap is not False
            else _StreamedPhase
        )
        self._stream = phase_cls(
            plan,
            overlap=train.phase_overlap if overlap is None else bool(overlap),
        )
        return self._stream

    def on_rollouts_landed(self) -> None:
        """Orchestrator hook, called after each rollout chunk lands in the
        buffer: dispatch every epoch-1 minibatch whose rows now exist.
        No-op outside a streamed phase or in serial (parity) mode."""
        st = self._stream
        if st is None or not st.overlap:
            return
        self._dispatch_ready_minibatches()

    def _dispatch_ready_minibatches(self, force: bool = False) -> None:
        st = self._stream
        plan = st.plan
        is_async = isinstance(st, _AsyncStreamedPhase)
        landed = len(self.buffer)
        while st.next_mb < plan.n_minibatches and (
            force or plan.ready(st.next_mb, landed)
        ):
            if is_async and not force:
                # version-lag guard (trainer/async_rl.py::guard_allows):
                # defer consumption whenever advancing the learner would
                # push any in-flight rollout's staleness past the
                # window. staleness_window=0 defers EVERYTHING while the
                # actors work — the bitwise-serial degenerate mode.
                from trlx_tpu.trainer.async_rl import guard_allows

                engine = self._rollout_engine_obj
                inflight = (
                    engine.min_inflight_version()
                    if engine is not None
                    else None
                )
                if not guard_allows(
                    st.learner_version,
                    inflight,
                    self.async_config.staleness_window,
                ):
                    # learner-idle attribution: rows are ready, the
                    # guard is what's holding them
                    if st.t_guard_hold is None:
                        st.t_guard_hold = telemetry.monotonic()
                    return
            if is_async and st.t_guard_hold is not None:
                st.guard_hold_ms += (
                    telemetry.monotonic() - st.t_guard_hold
                ) * 1000.0
                st.t_guard_hold = None
            # one span per epoch-1 dispatch: during collection these nest
            # strictly inside the phase/collect span (via collect/land),
            # which is how the trace shows what overlapped with what;
            # forced so the window mark survives a disabled tracer
            with telemetry.span(
                "train/epoch1_dispatch", force=True, minibatch=st.next_mb
            ) as sp:
                mb = self.buffer.gather(
                    plan.epoch1[st.next_mb], sharding=self._batch_sh
                )
                self.state, stats = self._train_step_jit(self.state, mb)
            if st.t_first_dispatch is None:
                st.t_first_dispatch = sp.start
            st.epoch1_stats.append(stats)
            st.next_mb += 1
            if is_async:
                self._after_async_update(st, plan, sp)

    def _after_async_update(
        self, st: "_AsyncStreamedPhase", plan: StreamPlan, sp
    ) -> None:
        """Async actor–learner bookkeeping after one consumed epoch-1
        minibatch: record its staleness (learner version at consumption
        minus the oldest behavior version among its rows), advance the
        learner version, and — while the actors still have work in
        flight — push the refreshed weights to the engine
        mid-generation (the in-flight update; the engine applies it at
        its harvest→admit safe point). No push once the actors are
        drained OR collection is closed: it could change nothing this
        plan consumes, and skipping it is what makes the
        staleness_window=0 run bitwise-serial (zero pushes ⇒ rollouts
        identical to the serial baseline — including when a
        chunk-rounded over-submission leaves rows in flight at the
        forced drain)."""
        # consumption lag (PipelineRL's "how old is the data"): learner
        # updates between a minibatch's oldest row being GENERATED and
        # it being trained — read from the stream store's version
        # column. Bounded by the plan (serial PPO has the same lag),
        # reported for attribution, never guarded on.
        consumed = plan.epoch1[st.next_mb - 1]
        st.consumed_lag.append(
            int(
                st.learner_version
                - int(self.buffer.row_versions(consumed).min())
            )
        )
        st.learner_version += 1
        st.learner_busy_ms += sp.duration_ms
        if st.collect_done:
            # post-collection (forced drain): nothing in flight can land
            # into this plan — the bounded in-flight lag is vacuously 0
            # and a push could only perturb the NEXT phase's snapshot
            st.staleness.append(0)
            return
        engine = self._rollout_engine_obj
        # the bounded quantity — in-flight generation lag AFTER this
        # update: how many learner versions ahead of the oldest rollout
        # still being generated the policy now is. The guard admitted
        # this update, so the recorded value is <= staleness_window by
        # construction; the staleness-breach detector watching the
        # phase max is therefore a true invariant check, not a tuning
        # knob. (Consumption lag — how many updates a LANDED row waits
        # before epoch-1 trains it — is bounded by the plan itself and
        # is not a staleness hazard: serial PPO has the same lag.)
        inflight = (
            engine.min_inflight_version() if engine is not None else None
        )
        st.staleness.append(
            0 if inflight is None
            else max(0, st.learner_version - int(inflight))
        )
        if engine is None or not engine.pending:
            return
        with telemetry.span(
            "async/weight_push", force=True, version=st.learner_version
        ) as push_sp:
            pushed = self._weight_push_jit(self.state.params)
            engine.push_weights(
                self._to_actor(pushed), version=st.learner_version
            )
        st.weight_pushes += 1
        st.learner_busy_ms += push_sp.duration_ms

    def finish_streamed_phase(
        self,
    ) -> Tuple[int, Dict[str, np.ndarray], List[float]]:
        """Close the active streamed phase: run everything the plan still
        owes (all of epoch 1 in serial mode; epochs 2..ppo_epochs always),
        advance the KL controller, and return ``(n_updates, rows,
        kl_seq)`` — ``rows`` maps each stats key to an [n_updates] host
        array in execution order (epoch-major: all epoch-1 updates, then
        epoch 2, ...)."""
        st = self._stream
        if st is None:
            raise RuntimeError("no streamed phase is active")
        method: PPOConfig = self.config.method
        train = self.config.train
        plan = st.plan

        # All phase timing below is span-sourced (telemetry/tracer.py):
        # the spans ARE the stopwatches — the same records feed the trace
        # exporter, bench's span payload, and the --perf-audit lockfile.
        # Forced spans still measure when the tracer is disabled (the
        # exp/overlap_* stats stay correct), they just go unrecorded.
        residual_stats = None
        residual_ms = 0.0
        if isinstance(st, _AsyncStreamedPhase):
            st.collect_done = True
        with telemetry.span(
            "phase/train", force=True, updates=plan.n_updates
        ) as train_sp:
            t_collect_end = train_sp.start
            st.dispatched_during_collect = st.next_mb
            # Drain: how long the host still waits on epoch-1 device work
            # after collection ended (tail dispatches included). A serial
            # schedule pays the WHOLE epoch-1 compute here; overlap pays
            # only the unhidden tail. The fence was always at this
            # boundary — the span adds no new sync.
            with telemetry.span("train/drain", force=True) as drain_sp:
                self._dispatch_ready_minibatches(force=True)
                jax.block_until_ready(st.epoch1_stats[-1])
            drain_ms = drain_sp.duration_ms

            # the snapshot is dead weight for the residual epochs — drop
            # our reference before the fused dispatch (in-flight consumers
            # keep the device buffers alive until they complete)
            self._behavior_params = None

            if plan.residual.size:
                mbs = self.buffer.gather(
                    plan.residual, sharding=self._stacked_batch_sh
                )
                with telemetry.span("train/residual", force=True) as res_sp:
                    self.state, residual_stats = self._train_phase_jit(
                        self.state, mbs
                    )
                    jax.block_until_ready(self.state.params)
                residual_ms = res_sp.duration_ms

            # one transfer event for every host consumer of the phase
            e1_rows, res_rows, mean_kl = jax.device_get(
                (st.epoch1_stats, residual_stats, self.mean_kl)
            )
        rows: Dict[str, np.ndarray] = {}
        for key in e1_rows[0]:
            seq = np.stack([np.asarray(r[key]) for r in e1_rows])
            if res_rows is not None:
                seq = np.concatenate([seq, np.asarray(res_rows[key])])
            rows[key] = seq

        # adaptive KL controller: one update per minibatch, compounding as
        # the stepwise/fused paths do — it only feeds the NEXT collection,
        # so advancing it after the phase is exact
        self._last_phase_mean_kl = float(mean_kl)
        kl_seq = [float(self.kl_coef)]
        for _ in range(plan.n_minibatches):
            kl_seq.append(float(kl_controller_update(
                method, kl_seq[-1], self._last_phase_mean_kl,
                train.batch_size,
            )))
        self.kl_coef = kl_seq[-1]

        # Overlap attribution (exp/overlap_saved_ms). Ground truth is the
        # interleaved A/B (ab_phase_overlap.py); these stats are the
        # cheap per-phase estimate: epoch-1 serial cost is taken from the
        # residual pass (same programs, (ppo_epochs-1) identical epochs)
        # when available, else bounded by the dispatch window. Every term
        # is span-derived: drain/residual from their span durations, the
        # window from the first epoch-1 dispatch span's start mark.
        window_ms = (
            max(0.0, (t_collect_end - st.t_first_dispatch) * 1000.0)
            if st.t_first_dispatch is not None
            else 0.0
        )
        if method.ppo_epochs > 1 and residual_ms > 0.0:
            epoch1_est_ms = residual_ms / (method.ppo_epochs - 1)
            saved_ms = max(0.0, epoch1_est_ms - drain_ms)
        else:
            saved_ms = max(0.0, window_ms - drain_ms)
        self._last_overlap_stats = {
            "exp/overlap_saved_ms": saved_ms,
            "exp/overlap_drain_ms": drain_ms,
            "exp/overlap_window_ms": window_ms,
            "exp/overlap_streamed_updates": float(
                st.dispatched_during_collect
            ),
            "exp/phase_residual_ms": residual_ms,
        }
        # allocator gauges next to the phase timing (empty on backends
        # without memory_stats, e.g. CPU): live/peak HBM per phase rides
        # the same stats row the spans feed
        from trlx_tpu.telemetry.device_metrics import phase_memory_stats

        self._last_overlap_stats.update(phase_memory_stats())

        # async actor–learner attribution (docs/async_pipeline.md):
        # staleness distribution over consumed epoch-1 minibatches,
        # learner idle (post-collect drain + time row-ready minibatches
        # sat behind the version-lag guard), actor/learner occupancy,
        # and the in-flight push count. async/staleness (the max) is
        # the staleness-breach detector's series.
        async_staleness_max: Optional[float] = None
        if isinstance(st, _AsyncStreamedPhase):
            st.learner_busy_ms += residual_ms
            staleness = np.asarray(st.staleness or [0], np.float64)
            lag = np.asarray(st.consumed_lag or [0], np.float64)
            wall_ms = max(
                (telemetry.monotonic() - st.t_begin) * 1000.0, 1e-9
            )
            async_staleness_max = float(staleness.max())
            engine = self._rollout_engine_obj
            self._last_overlap_stats.update({
                "async/staleness_p50": float(np.percentile(staleness, 50)),
                "async/staleness_max": async_staleness_max,
                "async/consumed_lag_p50": float(np.percentile(lag, 50)),
                "async/consumed_lag_max": float(lag.max()),
                "async/weight_pushes": float(st.weight_pushes),
                "async/guard_hold_ms": st.guard_hold_ms,
                "async/learner_idle_ms": drain_ms + st.guard_hold_ms,
                "async/learner_occupancy": min(
                    st.learner_busy_ms / wall_ms, 1.0
                ),
                "async/actor_occupancy": (
                    engine.stats.slot_util if engine is not None else 0.0
                ),
            })

        self._stream = None

        # unified metrics namespace: the phase's overlap/async/memory
        # attribution stats become registry gauges (async/guard_hold_ms,
        # async/learner_idle_ms, mem/hbm_* — the bubble-breakdown
        # inputs), snapshot-able by the ledger/flight recorder/bench
        telemetry.get_metrics().absorb(self._last_overlap_stats)

        # run-health: feed every fetched update row to the detector
        # engine in execution order, the phase-level rollout KL (the
        # kl-spike series) once per phase, then append the phase's
        # flight record. This lives HERE — not in _learn_body — so
        # direct drivers of the phase API (bench, the perf/health-smoke
        # harnesses) get monitoring without running learn(). Host
        # floats only: the single batched fetch above already paid the
        # transfer. The phase state is closed first so an `abort`
        # policy raising out of observe_health leaves the trainer
        # re-enterable.
        if self.health_monitor is not None:
            phase_id = self.health_phase_id
            last_row: Dict[str, Any] = {}
            phase_row: Dict[str, Any] = {
                "policy/mean_rollout_kl": self._last_phase_mean_kl
            }
            if async_staleness_max is not None:
                # the staleness-breach circuit-breaker's series: one
                # observation per phase (kind "above" is always armed)
                phase_row["async/staleness"] = async_staleness_max
            try:
                last_row = self.observe_health_rows(
                    rows,
                    phase=phase_id,
                    phase_row=phase_row,
                )
            finally:
                self.record_flight_phase(
                    phase_id, stats_row=last_row, kl_seq=kl_seq
                )

        return plan.n_updates, rows, kl_seq

    def _stream_eligible(self, iter_count: int) -> bool:
        """Whether the NEXT collect+train pass can run as a streamed phase:
        overlap enabled, an orchestrator attached, at least one planned
        minibatch, no profiler trace wanted, and no eval/checkpoint
        boundary or total_steps cutoff strictly inside the pass (those
        fall back to the legacy fused/stepwise paths, which honor
        mid-pass cadence)."""
        train = self.config.train
        method: PPOConfig = self.config.method
        # profile_dir WITHOUT profile_phase is the legacy first-10-steps
        # trace, which needs the stepwise path; the single-phase window
        # (profile_phase) profiles the streamed schedule itself
        legacy_profile = train.profile_dir and train.profile_phase is None
        if not train.phase_overlap or self.orch is None or legacy_profile:
            return False
        n_mb = method.num_rollouts // train.batch_size
        if n_mb < 1:
            return False
        pass_steps = n_mb * method.ppo_epochs
        total_steps = min(
            train.total_steps, train.epochs * pass_steps
        )
        if iter_count + pass_steps > total_steps:
            return False
        # interior MINIBATCH boundaries only — the same set the fused
        # path's gate checks: no execution path can evaluate/save at a
        # mid-minibatch step, so an interval multiple landing there must
        # not disable streaming for the whole run
        for k in range(1, n_mb):
            s = iter_count + method.ppo_epochs * k
            if s % train.eval_interval == 0 or s % train.checkpoint_interval == 0:
                return False
        return True

    def abort_streamed_phase(self) -> None:
        """Error-recovery escape hatch: drop an active streamed phase
        without running its remaining updates. Clears the plan and the
        behavior snapshot and empties the buffer (a partial phase's
        experience cannot satisfy the plan). Epoch-1 updates already
        dispatched are NOT rolled back — on-policy semantics of the next
        phase are unaffected since it snapshots afresh."""
        self._stream = None
        self._behavior_params = None
        self.buffer.clear_history()

    def _collect_phase(self, iter_count: int, seed: int) -> None:
        """Collect one phase of experience — streamed (the default) when
        the coming pass is eligible, else the plain serial collection the
        legacy train paths consume. A collection failure aborts the
        stream so a caller's retry starts from a clean slate instead of
        wedging on the stale plan."""
        # each collection opens a new phase; the profiler window (if one
        # is configured for this phase index) starts before any of the
        # phase's device work dispatches
        self._phase_index += 1
        self._phase_profiler.on_phase_start(self._phase_index)
        # non-streamed collections need the per-row phase reset too
        # (begin_streamed_phase repeats it harmlessly for streamed ones)
        self.reset_rollout_phase()
        if self._stream_eligible(iter_count):
            self.begin_streamed_phase(seed=seed)
        try:
            self.orch.make_experience(
                self.config.method.num_rollouts, iter_count
            )
        except BaseException:
            if self._stream is not None:
                self.abort_streamed_phase()
            raise

    def learn(self) -> Dict[str, Any]:
        """PPO optimization loop (reference `accelerate_base_model.py:224-305`
        + `accelerate_ppo_model.py:130-156`): per-epoch buffer pass with
        ``ppo_epochs`` updates per minibatch, on-policy refresh each epoch."""
        train = self.config.train
        method: PPOConfig = self.config.method

        # resume (reference Ray session restore, `accelerate_base_model.py:
        # 232-240`): restore params/opt/step + KL-controller state, continue
        # the step count from the checkpoint
        if train.resume_from_checkpoint and has_checkpoint(train.checkpoint_dir):
            self.load(train.checkpoint_dir)
            if int(self.state.step) >= train.total_steps:
                # finished run: skip rollout collection entirely
                self._final_stats = {}
                return {}

        # single-phase profiler window (train.profile_phase): constructed
        # before the initial collection so phase 0 is profileable
        from trlx_tpu.telemetry.profiler import PhaseProfiler

        self._phase_index = -1
        self._phase_profiler = PhaseProfiler(
            train.profile_dir, train.profile_phase
        )

        # the loop's step counter must come from BEFORE any streamed
        # epoch-1 update advances state.step during the initial collection
        start_step = int(self.state.step)
        # Resume alignment (kill/resume parity, docs/resilience.md): a
        # run resumed at the end of epoch k must collect its next phase
        # with the SAME seed the uninterrupted run would (train.seed +
        # phase index) and run the epoch loop from k, not 0 — otherwise
        # the resumed run replays phase-0 prompts/shuffles and diverges
        # from the run it is continuing. The per-pass step count is
        # derived from the config (the streamed plan uses the same
        # numbers), so the mapping needs no buffer state. The floor
        # assumes the checkpoint sits on a pass boundary — true for
        # preemption-drain and end-of-pass cadence saves; a MID-pass
        # stepwise-cadence checkpoint resumes at its enclosing pass
        # boundary's schedule (the partial pass is re-collected fresh —
        # valid PPO, but bitwise parity is only guaranteed for
        # boundary checkpoints, docs/resilience.md).
        pass_steps = method.ppo_epochs * max(
            method.num_rollouts // train.batch_size, 1
        )
        self._epoch0 = start_step // pass_steps if start_step else 0
        if len(self.buffer) == 0 and self.orch is not None:
            self._collect_phase(start_step, seed=train.seed + self._epoch0)

        if self._stream is not None:
            # streamed phases advance iter_count by the PLAN's update
            # count; rows a non-dividing final chunk over-collects are
            # stored but never scheduled, so sizing the loop from
            # len(buffer) would set a total_steps the phases can never
            # reach (skipping the end-of-run save + eval)
            n_minibatches = self._stream.plan.n_minibatches
        else:
            n_minibatches = max(len(self.buffer) // train.batch_size, 1)
        total_steps = min(
            train.total_steps, train.epochs * method.ppo_epochs * n_minibatches
        )

        logger = Logger(
            project_name=train.project_name,
            run_name=train.run_name,
            config=self.config.to_dict(),
            tags=train.tags,
            total_steps=total_steps,
        )
        self.logger = logger
        self._profiling = False
        try:
            result = self._learn_body(
                logger, total_steps, n_minibatches, start_step
            )
        except BaseException as e:
            # crash forensics: one flight dump per run on the way down
            # (telemetry/flight_recorder.py; no-op when health is off,
            # deduped when a HealthAbort's detector already dumped)
            self.flight_dump_on_exception(e)
            # run ledger (telemetry/run_ledger.py): failed runs are
            # history too — the manifest records the error outcome
            self.append_run_ledger(status="error", error=e)
            raise
        else:
            self.append_run_ledger(status="ok")
            return result
        finally:
            # single epilogue for every exit (incl. exceptions): stop any
            # live profiler trace (legacy first-steps AND the single-phase
            # window), join in-flight async checkpoint writes (surfacing
            # background write errors), close the logger even if that
            # join raises
            try:
                self._phase_profiler.close()
                if self._profiling:
                    jax.profiler.stop_trace()
                    self._profiling = False
            finally:
                try:
                    wait_for_checkpoints()
                finally:
                    logger.finish()

    def _end_of_pass(
        self,
        logger: Logger,
        iter_count: int,
        total_steps: int,
        final_stats: Dict[str, Any],
        epoch: int,
    ) -> Tuple[Dict[str, Any], bool]:
        """Shared epilogue of a whole-pass branch (streamed or fused) of
        ``_learn_body``: interval-gated eval/save at the pass boundary,
        the end-of-run save + final eval, and the on-policy refresh for
        the next epoch. Returns ``(final_stats, done)`` — ``done`` means
        the run is complete and the caller must return."""
        train = self.config.train
        iv = self.intervals(iter_count)
        if iv["do_save"] and iter_count >= total_steps:
            # the end-of-run branch below saves this same step
            iv["do_save"] = False
        if iv["do_eval"]:
            eval_stats = self.evaluate()
            logger.log(eval_stats, step=iter_count)
            final_stats.update(eval_stats)
        if iv["do_save"]:
            self.save()
        if iter_count >= total_steps:
            self.save()
            eval_stats = self.evaluate()
            logger.log(eval_stats, step=iter_count)
            final_stats.update(eval_stats)
            self._final_stats = final_stats
            return final_stats, True
        if self.orch is not None and epoch < train.epochs - 1:
            # preemption drain point (docs/resilience.md): AFTER this
            # boundary's eval/save (so the saved RNG chain includes any
            # eval sampling — kill/resume parity), BEFORE the next
            # phase's collection dispatches
            self.maybe_drain(phase=self._phase_index, step=iter_count)
            self.buffer.clear_history()
            self._collect_phase(iter_count, seed=train.seed + epoch + 1)
        return final_stats, False

    def _learn_body(
        self,
        logger: Logger,
        total_steps: int,
        n_minibatches: int,
        start_step: int = 0,
    ) -> Dict[str, Any]:
        train = self.config.train
        method: PPOConfig = self.config.method

        # (with a streamed phase active, the sampler serves the frozen
        # behavior snapshot — this eval reflects the pre-phase policy even
        # though epoch-1 updates may already be in flight). A mid-run
        # RESUME skips this step-0 eval: the uninterrupted run did not
        # evaluate at this point, and the extra eval would advance the
        # sampler RNG chain — breaking the bitwise kill/resume parity
        # the preemption drain guarantees (docs/resilience.md).
        if start_step == 0:
            stats = self.evaluate()
            logger.log(stats, step=0)
            if hasattr(self, "_last_samples"):
                logger.log_samples(
                    self._last_samples[1], self._last_samples[0], step=0
                )

        clock = Clock()
        iter_count = start_step  # nonzero after resume
        final_stats: Dict[str, Any] = {}
        self._final_stats = final_stats
        if iter_count >= total_steps:
            # resumed a finished run: nothing left to train
            return final_stats
        if train.profile_dir and train.profile_phase is None:
            # legacy mode: trace the first ~10 optimizer steps from loop
            # start (profile_phase traces one whole phase instead)
            jax.profiler.start_trace(train.profile_dir)
            self._profiling = True
        for epoch in range(getattr(self, "_epoch0", 0), train.epochs):
            # Streamed phase (the default): collection already interleaved
            # epoch-1 updates against the behavior snapshot; close the
            # phase (residual epochs + stats) and log per-minibatch
            # exactly like the fused path.
            if self._stream is not None:
                n_up, rows, kl_seq = self.finish_streamed_phase()
                phase_time = clock.tick(train.batch_size) / 1000.0
                self.check_anomalies(rows, iter_count)
                n_mb = n_up // method.ppo_epochs
                step_stats = {}
                for k in range(n_mb):
                    iter_count += method.ppo_epochs
                    # mb k's FINAL inner update: epoch-major order puts it
                    # in the last epoch's span (epoch-1 row k when E == 1)
                    row = (method.ppo_epochs - 1) * n_mb + k
                    step_stats = {
                        key: float(v[row]) for key, v in rows.items()
                    }
                    step_stats["time/batch"] = phase_time / n_mb
                    step_stats["policy/kl_coef"] = float(kl_seq[k + 1])
                    step_stats["policy/mean_rollout_kl"] = (
                        self._last_phase_mean_kl
                    )
                    step_stats.update(self._last_overlap_stats)
                    if iter_count % train.log_interval == 0:
                        logger.log(step_stats, step=iter_count)
                        final_stats = dict(step_stats)
                # phase boundary: the profiled phase's updates are done and
                # fetched — close the window here (no new sync)
                self._phase_profiler.on_phase_end(sync=self.state.params)
                final_stats, done = self._end_of_pass(
                    logger, iter_count, total_steps, final_stats, epoch
                )
                if done:
                    return final_stats
                continue
            # Fused path: the whole buffer pass is one device dispatch
            # (lax.scan over minibatches) — used whenever no eval/save
            # boundary or total_steps cutoff falls strictly inside the pass
            # (log cadence is honored post-hoc from the stacked stats).
            pass_steps = method.ppo_epochs * n_minibatches
            interior = [
                iter_count + method.ppo_epochs * k
                for k in range(1, n_minibatches)
            ]
            fused_ok = (
                not self._profiling
                and len(self.buffer) >= train.batch_size
                and iter_count + pass_steps <= total_steps
                and not any(
                    s % train.eval_interval == 0
                    or (s > 0 and s % train.checkpoint_interval == 0)
                    for s in interior
                )
            )
            if fused_ok:
                # phase/train on the fused path covers dispatch AND the
                # stats fetch that forces it — the same window the
                # streamed path's span measures
                with telemetry.span(
                    "phase/train", force=True,
                    updates=n_minibatches * method.ppo_epochs,
                ):
                    _, stacked, kl_seq = self.train_on_buffer(
                        seed=train.seed + epoch, n_minibatches=n_minibatches
                    )
                    # one transfer event for the whole stacked stats tree
                    # + KL state (per-key np.asarray would pay ~100ms per
                    # leaf on a tunneled chip)
                    rows, kl_seq, mean_kl = jax.device_get(
                        (stacked, kl_seq, self.mean_kl)
                    )
                phase_time = clock.tick(train.batch_size) / 1000.0
                # every fetched update row feeds the detectors (the
                # streamed path does the same in finish_streamed_phase);
                # the phase-constant rollout KL is observed once. BEFORE
                # check_anomalies: on a NaN row the nan-precursor trip +
                # flight-recorder policy must see the offending phase
                # before the anomaly abort raises
                self.observe_health_rows(
                    rows,
                    step0=iter_count,
                    phase=self._phase_index,
                    phase_row={"policy/mean_rollout_kl": float(mean_kl)},
                )
                self.check_anomalies(rows, iter_count)
                step_stats = {}
                for k in range(n_minibatches):
                    iter_count += method.ppo_epochs
                    # the stepwise loop logs the last inner update per mb
                    row = k * method.ppo_epochs + method.ppo_epochs - 1
                    step_stats = {key: float(v[row]) for key, v in rows.items()}
                    step_stats["time/batch"] = phase_time / n_minibatches
                    step_stats["policy/kl_coef"] = float(kl_seq[k + 1])
                    step_stats["policy/mean_rollout_kl"] = float(mean_kl)
                    if iter_count % train.log_interval == 0:
                        logger.log(step_stats, step=iter_count)
                        final_stats = dict(step_stats)
                self.record_flight_phase(
                    self._phase_index, step=iter_count,
                    stats_row=step_stats, kl_seq=list(kl_seq),
                )
                self._phase_profiler.on_phase_end(sync=self.state.params)
                final_stats, done = self._end_of_pass(
                    logger, iter_count, total_steps, final_stats, epoch
                )
                if done:
                    return final_stats
                continue

            step_stats = {}
            for mb in self.buffer.create_loader(
                train.batch_size,
                shuffle=True,
                seed=train.seed + epoch,
                sharding=batch_sharding(self.mesh),
            ):
                for _ in range(method.ppo_epochs):
                    self.state, step_stats = self._train_step_jit(self.state, mb)
                    iter_count += 1
                step_stats["time/batch"] = clock.tick(train.batch_size) / 1000.0
                # adaptive KL controller (post_backward_callback,
                # `accelerate_ppo_model.py:136-137`) — stays device-side;
                # the do_log branch fetches everything in one event
                self.kl_coef = kl_controller_update(
                    method, self.kl_coef, self.mean_kl, train.batch_size
                )
                step_stats["policy/kl_coef"] = self.kl_coef
                step_stats["policy/mean_rollout_kl"] = self.mean_kl

                if self._profiling and iter_count >= 10:
                    jax.block_until_ready(self.state.params)
                    jax.profiler.stop_trace()
                    self._profiling = False

                iv = self.intervals(iter_count)
                at_end = iter_count >= total_steps
                if iv["do_log"] or iv["do_save"] or at_end:
                    # ONE stats fetch per step, shared by every host
                    # consumer (logger, anomaly check before save) — the
                    # log and save branches each paying their own
                    # device_get doubled/tripled the host round-trips
                    step_stats = jax.device_get(step_stats)
                    # detectors read the same fetched row — still the
                    # one transfer this step already paid, and BEFORE
                    # check_anomalies so a NaN row reaches nan-precursor
                    # and the flight policy before the anomaly abort.
                    # The rollout KL is phase-constant, so it is
                    # excluded here and observed once at the pass
                    # boundary below (per-row repeats would collapse
                    # its EWMA variance)
                    self.observe_health(
                        {
                            k: v for k, v in step_stats.items()
                            if k != "policy/mean_rollout_kl"
                        },
                        step=iter_count, phase=self._phase_index,
                    )
                    # never log or persist a NaN state
                    self.check_anomalies(step_stats, iter_count)
                if iv["do_log"]:
                    logger.log(step_stats, step=iter_count)
                    final_stats = {k: float(v) for k, v in step_stats.items()}
                if iv["do_eval"]:
                    eval_stats = self.evaluate()
                    logger.log(eval_stats, step=iter_count)
                    final_stats.update(eval_stats)
                if iv["do_save"] and not at_end:
                    # at_end saves below — don't serialize the same step's
                    # full sharded state twice when the intervals coincide
                    self.save()
                if at_end:
                    self.save()
                    eval_stats = self.evaluate()
                    logger.log(eval_stats, step=iter_count)
                    final_stats.update(eval_stats)
                    self._final_stats = final_stats
                    return final_stats
            # stepwise pass done — phase boundary: the phase-level KL
            # series gets its ONE observation (skipped by the monitor if
            # the value never crossed to host this pass), then the
            # flight record (device leaves in an unfetched last row are
            # dropped by the recorder, never forced)
            if self.health_monitor is not None and step_stats:
                self.observe_health(
                    {
                        "policy/mean_rollout_kl": step_stats.get(
                            "policy/mean_rollout_kl"
                        )
                    },
                    step=iter_count, phase=self._phase_index,
                )
            self.record_flight_phase(
                self._phase_index, step=iter_count, stats_row=step_stats
            )
            self._phase_profiler.on_phase_end(sync=self.state.params)
            # on-policy refresh (post_epoch_callback,
            # `accelerate_ppo_model.py:130-134`)
            if self.orch is not None and epoch < train.epochs - 1:
                # preemption drain point: same boundary as the
                # streamed/fused paths' _end_of_pass
                self.maybe_drain(phase=self._phase_index, step=iter_count)
                self.buffer.clear_history()
                self._collect_phase(iter_count, seed=train.seed + epoch + 1)
        self._final_stats = final_stats
        return final_stats

    # ------------------------------------------------------------------ #

    def host_state_dict(self) -> Dict[str, Any]:
        state = super().host_state_dict()
        # per-row RNG phase state: mid-phase the lazily split phase key
        # and draw cursor decide every remaining row's fold_in key, so
        # a boundary-agnostic checkpoint must carry them (at a phase
        # boundary they are just None/0 and the entry is inert)
        if self._rollout_phase_key is not None:
            state["rollout_phase_key"] = (
                np.asarray(jax.device_get(self._rollout_phase_key))
                .ravel()
                .tolist()
            )
        state["rollout_row_cursor"] = int(self._rollout_row_cursor)
        # continuous-engine drafter: accept-EWMA/probe counters feed the
        # drafting schedule (spec_drafter.state_dict); only present once
        # the engine has been built — a never-built engine has no
        # drafter state worth carrying
        engine = self._rollout_engine_obj
        drafter = getattr(engine, "spec_drafter", None)
        if drafter is not None and hasattr(drafter, "state_dict"):
            state["spec_drafter"] = drafter.state_dict()
        return state

    def load_host_state_dict(self, state: Dict[str, Any]) -> None:
        super().load_host_state_dict(state)
        phase_key = state.get("rollout_phase_key")
        if phase_key is not None:
            self._rollout_phase_key = jnp.asarray(
                np.asarray(phase_key, dtype=np.uint32)
            )
        self._rollout_row_cursor = int(
            state.get("rollout_row_cursor", self._rollout_row_cursor)
        )
        drafter_state = state.get("spec_drafter")
        if drafter_state is not None and self.rollout_engine == "continuous":
            # building the engine here is fine: a resumed
            # continuous-engine run needs it before the first phase
            # anyway, and restoring the drafter EWMAs after that first
            # phase would be too late
            drafter = getattr(self.rollout_engine_obj, "spec_drafter", None)
            if drafter is not None and hasattr(drafter, "load_state_dict"):
                drafter.load_state_dict(drafter_state)

    def _save_metadata(self) -> Dict[str, Any]:
        """The checkpoint's host-metadata pytree (JSON-safe). Split out
        of save() so the resume auditor (engine 15) can fingerprint the
        metadata schema for the ``state_manifest`` lock without writing
        a checkpoint."""
        # one batched fetch for all host-side save inputs
        kl_coef, mean_kl, rng = jax.device_get(
            (self.kl_coef, self.mean_kl, self.rng)
        )
        metadata = {
            "kl_coef": float(kl_coef),
            "mean_kl": float(mean_kl),
            # the sampler RNG chain: one split per phase (plus one
            # per chunk without per-row RNG) — restoring it exactly
            # is half of kill/resume bitwise parity; the other half
            # is the orchestrator state below (docs/resilience.md)
            "rng_key": np.asarray(rng).ravel().tolist(),
            # everything else mutable-but-host-side (drafter EWMAs,
            # health detectors, mid-phase RNG cursor) rides the
            # host-state contract audited by engine 15
            "host_state": self.host_state_dict(),
        }
        orch = getattr(self, "orch", None)
        if orch is not None and hasattr(orch, "state_dict"):
            # reward-scaling running moments + prompt-stream position
            metadata["orchestrator"] = orch.state_dict()
        return metadata

    def save(self, directory: Optional[str] = None) -> None:
        directory = directory or self.config.train.checkpoint_dir
        with telemetry.span("phase/checkpoint"):
            step = int(jax.device_get(self.state.step))
            save_checkpoint(
                directory,
                self.state,
                metadata=self._save_metadata(),
                async_save=self.config.train.async_checkpoint,
                step=step,
            )

    def load(self, directory: str) -> None:
        abstract = jax.tree_util.tree_map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            self.state,
            self.state_shardings,
        )
        self.state, meta = load_checkpoint(directory, abstract)
        self.kl_coef = float(meta.get("kl_coef", self.kl_coef))
        self.mean_kl = float(meta.get("mean_kl", self.mean_kl))
        rng_key = meta.get("rng_key")
        if rng_key is not None:
            self.rng = jnp.asarray(
                np.asarray(rng_key, dtype=np.uint32).reshape(
                    np.shape(self.rng)
                )
            )
        orch_state = meta.get("orchestrator")
        orch = getattr(self, "orch", None)
        if orch_state and orch is not None and hasattr(orch, "load_state_dict"):
            orch.load_state_dict(orch_state)
        self.load_host_state_dict(meta.get("host_state") or {})
