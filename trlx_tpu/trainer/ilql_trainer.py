"""ILQL trainer: offline Q-learning with jitted updates and in-graph
target-network sync.

Re-design of ``AccelerateILQLModel`` (``trlx/model/accelerate_ilql_model.py``):

- The target-Q param tree is part of the train state; the Polyak sync every
  ``steps_for_target_q_sync`` steps (`accelerate_ilql_model.py:54-56`,
  `ilql_models.py:161-181`) is a ``lax.cond`` *inside* the jitted train step
  — no host round-trip, no ZeRO gather (sharded params sync elementwise).
- Evaluation generation uses the compiled sampler with advantage-shifted
  logits ``log pi_beta + beta * (min_target_Q - V)`` and optional per-token
  ``logit_mask`` (the reference's hand-rolled decode,
  `ilql_models.py:257-327`).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import flax.struct as struct
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.data.ilql_types import ILQLBatch
from trlx_tpu.models.heads import CausalLMWithILQLHeads
from trlx_tpu.models.registry import num_layers_of
from trlx_tpu.ops.ilql_math import ILQLConfig, ilql_loss, polyak_update
from trlx_tpu.ops.sampling import GenerationConfig, make_sampler, validate_gen_config
from trlx_tpu.parallel import (
    batch_sharding,
    make_partition_specs,
    make_mesh,
    replicated,
)
from trlx_tpu.trainer import BaseRLTrainer, register_trainer
from trlx_tpu.trainer.common import (
    make_optimizer,
    stop_frozen_gradients,
    unfrozen_param_mask,
)
from trlx_tpu.utils import Clock, set_seed
from trlx_tpu.utils.checkpoint import (
    has_checkpoint,
    load_checkpoint,
    save_checkpoint,
    wait_for_checkpoints,
)
from trlx_tpu.utils.logging import Logger


@struct.dataclass
class ILQLTrainState:
    params: Any
    target_q_params: Any  # copy of the q-head subtree of params["heads"]
    opt_state: Any
    step: jax.Array


def _q_subtree(heads_params: Dict) -> Dict:
    return {k: v for k, v in heads_params.items() if k.startswith("q")}


@register_trainer
class ILQLTrainer(BaseRLTrainer):
    def __init__(
        self,
        config: TRLConfig,
        reward_fn: Optional[Callable] = None,
        metric_fn: Optional[Callable] = None,
        tokenizer=None,
        logit_mask=None,
    ):
        super().__init__(config, reward_fn, metric_fn, tokenizer, logit_mask)
        method: ILQLConfig = config.method
        train = config.train

        if (train.rollout or {}).get("engine", "fixed") != "fixed":
            # ILQL is offline — there is no rollout collect loop for the
            # continuous engine to drive; refuse instead of no-opping
            raise NotImplementedError(
                "train.rollout engine "
                f"{train.rollout.get('engine')!r} is not supported by "
                "ILQLTrainer (offline trainer; no rollout engine)"
            )
        if (train.async_rl or {}).get("enabled"):
            # same loudness: no collect phase to disaggregate
            raise NotImplementedError(
                "train.async_rl is not supported by ILQLTrainer "
                "(offline trainer; there is no actor/collect loop to "
                "run asynchronously)"
            )
        self.mesh = make_mesh(train.mesh)
        self.pp_stages = dict(self.mesh.shape).get("pp", 1)
        self.pp_microbatches = train.pp_microbatches
        self.pp_virtual_stages = train.pp_virtual_stages
        self.pp_remat = train.pp_remat
        if self.pp_remat and self.pp_virtual_stages > 1:
            raise NotImplementedError(
                "pp_remat runs the v=1 schedule; drop pp_virtual_stages "
                "or pp_remat"
            )
        self.rng = set_seed(train.seed)

        if tokenizer is None and config.model.tokenizer_path:
            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(
                config.model.tokenizer_path, local_files_only=True
            )
            if self.tokenizer.pad_token_id is None:
                self.tokenizer.pad_token = self.tokenizer.eos_token

        from trlx_tpu.trainer.ppo_trainer import get_causal_arch

        self.family, self.model_config, init_params = get_causal_arch(config)
        if self.pp_stages > 1:
            from trlx_tpu.models.pp_runner import supports_pp

            if not supports_pp(self.model_config):
                # without this guard a pp axis would silently replicate all
                # compute across the pp devices (rules never reference pp)
                raise NotImplementedError(
                    f"pp mesh axis is integrated for the causal families "
                    f"(gpt2/gptj/gpt_neo/gpt_neox) but not "
                    f"{type(self.model_config).__name__}: MoE layers have "
                    f"non-uniform per-layer params (no stage stacking); "
                    f"use dp/fsdp/tp/ep instead"
                )
        self.model = CausalLMWithILQLHeads(
            self.model_config,
            two_qs=method.two_qs,
            backbone_cls=self.family.backbone_cls,
        )

        # sampling defaults live in ILQLConfig.gen_kwargs (config-visible,
        # merged by ILQLConfig.from_dict); re-merge here too so code that
        # assigns config.method.gen_kwargs directly (examples do) still gets
        # the reference's eval-decode defaults (top_k=20, ...) under its
        # own keys rather than silently losing them
        from trlx_tpu.ops.ilql_math import DEFAULT_ILQL_GEN_KWARGS

        gen_kwargs = {**DEFAULT_ILQL_GEN_KWARGS, **(method.gen_kwargs or {})}
        self.apply_tokenizer_gen_defaults(gen_kwargs)
        self.gen_config = GenerationConfig.from_dict(gen_kwargs)
        validate_gen_config(
            self.gen_config,
            getattr(self.model_config, "vocab_size", None),
            provided=set(gen_kwargs),
        )
        self.beta = float(method.betas[0])
        self.query_length = min(
            train.seq_length, max(train.seq_length - self.gen_config.max_new_tokens, 1)
        )

        # --- params / state ---
        self.rng, init_rng = jax.random.split(self.rng)
        dummy = jnp.zeros((1, 8), jnp.int32)
        params = self.model.init(init_rng, dummy)["params"]
        if init_params is not None:
            params["transformer"] = init_params

        self.param_shardings = self._shardings_for(params)
        params = jax.device_put(params, self.param_shardings)
        target_q = jax.tree_util.tree_map(jnp.copy, _q_subtree(params["heads"]))
        self.target_shardings = self._shardings_for(target_q)
        target_q = jax.device_put(target_q, self.target_shardings)

        # zero_freezes_all: the reference's ILQL freezing is live code and
        # freezes ALL gpt blocks at num_layers_unfrozen == 0
        # (ilql_models.py:217-225) — unlike the PPO path, whose freezing
        # block is commented out (accelerate_base_model.py:55-69)
        trainable = unfrozen_param_mask(
            params,
            config.model.num_layers_unfrozen,
            num_layers_of(self.model_config),
            zero_freezes_all=True,
        )
        self.trainable_mask = trainable
        self.tx = make_optimizer(train, train.total_steps, trainable)
        opt_shapes = jax.eval_shape(self.tx.init, params)
        self.opt_shardings = self._shardings_for(opt_shapes)
        opt_state = jax.jit(self.tx.init, out_shardings=self.opt_shardings)(params)

        self.state = ILQLTrainState(
            params=params,
            target_q_params=target_q,
            opt_state=opt_state,
            step=jnp.zeros((), jnp.int32),
        )
        self.state_shardings = ILQLTrainState(
            params=self.param_shardings,
            target_q_params=self.target_shardings,
            opt_state=self.opt_shardings,
            step=replicated(self.mesh),
        )

        self.store = None  # installed by OfflineOrchestrator
        self.setup_ep_axis(self.mesh, self.family)
        self._setup_rollout_cast(train)
        self._build_jitted_fns()

    def _setup_rollout_cast(self, train) -> None:
        """Compute-dtype copy of the sampler bundle (params + target-Q) for
        the β(Q−V) decode — same contract as the PPO trainer's
        (`train.rollout_param_cast`): bit-identical (trunk ops cast per use;
        MLPHead fc2 leaves stay f32) and half the per-token weight read."""
        self._rollout_cast_jit = None
        self._rollout_bundle_cache = None
        cdtype = jnp.dtype(getattr(self.model_config, "dtype", train.dtype))
        pdtype = jnp.dtype(
            getattr(self.model_config, "param_dtype", train.param_dtype)
        )
        if (
            not getattr(train, "rollout_param_cast", False)
            or cdtype == pdtype
        ):
            return
        from trlx_tpu.utils import compute_dtype_cast

        bundle_shardings = {
            "params": self.param_shardings,
            "target": self.target_shardings,
        }
        self._rollout_cast_jit = jax.jit(
            lambda bundle: compute_dtype_cast(bundle, cdtype),
            in_shardings=(bundle_shardings,),
            out_shardings=bundle_shardings,
        )

    def rollout_bundle(self):
        """Sampler inputs: the compute-dtype copy when the cast is enabled
        (recast lazily — ILQLTrainState is replaced on update, so object
        identity detects staleness), else the f32 masters."""
        master = {
            "params": self.state.params,
            "target": self.state.target_q_params,
        }
        if self._rollout_cast_jit is None:
            return master
        cache = self._rollout_bundle_cache
        key = (master["params"], master["target"])
        if cache is None or cache[0][0] is not key[0] or cache[0][1] is not key[1]:
            self._rollout_bundle_cache = (key, self._rollout_cast_jit(master))
        return self._rollout_bundle_cache[1]

    def _shardings_for(self, tree):
        specs = make_partition_specs(tree, self.mesh, self.family.partition_rules)
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s),
            specs,
            is_leaf=lambda x: isinstance(x, P),
        )

    def _build_jitted_fns(self):
        method: ILQLConfig = self.config.method
        batch_sh = batch_sharding(self.mesh)
        rep = replicated(self.mesh)
        logit_mask = (
            jnp.asarray(self.logit_mask) if self.logit_mask is not None else None
        )

        moe_family = bool(getattr(self.family, "supports_ep", False))

        def train_step(state: ILQLTrainState, mb: ILQLBatch):
            def loss_fn(params):
                # prune the backward below the freezing boundary (reference
                # `ilql_models.py:217-225` freezes via requires_grad=False)
                params = stop_frozen_gradients(params, self.trainable_mask)
                if self.pp_stages > 1:
                    from trlx_tpu.models.pp_runner import pp_ilql_forward

                    out = pp_ilql_forward(
                        self.model_config, params, mb.input_ids,
                        mb.attention_mask, mb.actions_ixs, mb.states_ixs,
                        self.mesh, self.pp_microbatches,
                        two_qs=method.two_qs,
                        virtual_stages=self.pp_virtual_stages,
                        remat=self.pp_remat,
                    )
                elif moe_family:
                    out, sown = self.model.apply(
                        {"params": params},
                        mb.input_ids,
                        attention_mask=mb.attention_mask,
                        actions_ixs=mb.actions_ixs,
                        states_ixs=mb.states_ixs,
                        mutable=["moe_losses"],
                    )
                else:
                    out = self.model.apply(
                        {"params": params},
                        mb.input_ids,
                        attention_mask=mb.attention_mask,
                        actions_ixs=mb.actions_ixs,
                        states_ixs=mb.states_ixs,
                    )
                target_qs = self.model.apply(
                    {"params": {"heads": state.target_q_params}},
                    out["action_hidden"],
                    method=CausalLMWithILQLHeads.target_qs,
                )
                loss, stats = ilql_loss(
                    out["logits"], out["qs"], target_qs, out["vs"], mb,
                    method, health=self._health_enabled,
                )
                if moe_family:
                    # same Switch load-balancing objective as the PPO path
                    from trlx_tpu.models.gpt2_moe import (
                        apply_router_penalty, moe_loss_summary,
                    )

                    loss, stats = apply_router_penalty(
                        loss, stats, moe_loss_summary(sown["moe_losses"]),
                        self.model_config,
                    )
                return loss, stats

            (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params
            )
            updates, new_opt_state = self.tx.update(
                grads, state.opt_state, state.params
            )
            new_params = optax.apply_updates(state.params, updates)
            new_step = state.step + 1
            # in-graph Polyak target sync (`ilql_models.py:161-181`)
            new_target = jax.lax.cond(
                new_step % method.steps_for_target_q_sync == 0,
                lambda: polyak_update(
                    _q_subtree(new_params["heads"]),
                    state.target_q_params,
                    method.alpha,
                ),
                lambda: state.target_q_params,
            )
            stats["optimizer/grad_norm"] = optax.global_norm(grads)
            return (
                ILQLTrainState(
                    params=new_params,
                    target_q_params=new_target,
                    opt_state=new_opt_state,
                    step=new_step,
                ),
                stats,
            )

        self._train_step_jit = jax.jit(
            train_step,
            in_shardings=(self.state_shardings, batch_sh),
            out_shardings=(self.state_shardings, rep),
            donate_argnums=(0,),
        )

        # chunked fused scan: k consecutive updates in one dispatch (the
        # in-graph lax.cond target sync keys off state.step, so scanning
        # preserves the sync schedule exactly)
        from trlx_tpu.parallel.mesh import stacked_batch_sharding

        self._stacked_batch_sh = stacked_batch_sharding(self.mesh)

        def train_chunk(state, mbs):
            return jax.lax.scan(train_step, state, mbs)

        self._train_chunk_jit = jax.jit(
            train_chunk,
            in_shardings=(self.state_shardings, self._stacked_batch_sh),
            out_shardings=(self.state_shardings, rep),
            donate_argnums=(0,),
        )

        # --- advantage-shifted sampler (`ilql_models.py:257-327`) ---
        def shift_logits(raw_logits, qs_tuple, vs, input_ids, last_only):
            """β(Q−V)-shifted sampling logits + adjacency mask — shared by
            the plain and pp sampler applies."""
            minq = qs_tuple[0]
            for tq in qs_tuple[1:]:
                minq = jnp.minimum(minq, tq)
            adv = minq - vs[..., None]
            logits = jax.nn.log_softmax(raw_logits, axis=-1) + self.beta * adv
            if logit_mask is not None:
                ids = input_ids[:, -1:] if last_only else input_ids
                allowed = logit_mask[ids]  # [B, T or 1, V] bool
                logits = jnp.where(allowed, logits, -1e9)
            return logits

        if self.pp_stages > 1:
            # pp decode: trunk pipelined with stage-resident KV buffers;
            # logits + Q/V/target-Q heads replicated over pp at the last
            # position only (all the advantage-shifted decode reads)
            from trlx_tpu.models.heads import ILQLHeads
            from trlx_tpu.models.pp_runner import (
                pp_cached_hidden,
                pp_decode_kit,
                pp_slice_logits,
                pp_stack_sampler_params,
            )

            heads_mod = ILQLHeads(self.model_config, method.two_qs)

            def sample_apply(bundle, input_ids, attention_mask=None,
                             position_ids=None, cache=None, cache_index=None,
                             last_only=False):
                params = bundle["params"]
                h, new_cache = pp_cached_hidden(
                    self.model_config, params["transformer"], input_ids,
                    attention_mask, position_ids, cache, cache_index,
                    self.mesh, self.pp_microbatches,
                    stacked=params["stacked_blocks"],
                )
                hs = h[:, -1:]
                raw = pp_slice_logits(
                    self.model_config, params["transformer"], hs
                )
                # only V from the live heads; the advantage shift reads
                # target-Q (live Q heads would trace dead matmuls)
                vs = heads_mod.apply(
                    {"params": params["heads"]}, hs, method=ILQLHeads.v
                )
                target_qs = heads_mod.apply(
                    {"params": bundle["target"]}, hs, method=ILQLHeads.q
                )
                logits = shift_logits(raw, target_qs, vs, input_ids, True)
                return {"logits": logits, "cache": new_cache}

            init_cache_fn, cache_sharding = pp_decode_kit(
                self.model_config, self.mesh
            )
            inner = make_sampler(
                sample_apply,
                init_cache_fn,
                self.gen_config,
                self.query_length,
                with_values=False,
                cache_sharding=cache_sharding,
            )

            def sampler(bundle, prompt_ids, prompt_mask, rng):
                # stack/reshard the trunk blocks ONCE per invocation, not
                # once per decoded token inside the sampler's scan
                packed = pp_stack_sampler_params(
                    self.model_config, self.mesh, bundle["params"]
                )
                return inner(
                    {"params": packed, "target": bundle["target"]},
                    prompt_ids, prompt_mask, rng,
                )
        else:
            def sample_apply(bundle, input_ids, attention_mask=None,
                             position_ids=None, cache=None, cache_index=None,
                             last_only=False):
                # last_only (prefill): logits + Q/V heads only at the final
                # position — the advantage-shifted decode reads one row.
                out = self.model.apply(
                    {"params": bundle["params"]},
                    input_ids,
                    attention_mask=attention_mask,
                    position_ids=position_ids,
                    cache=cache,
                    cache_index=cache_index,
                    last_only=last_only,
                )
                target_qs = self.model.apply(
                    {"params": {"heads": bundle["target"]}},
                    out["action_hidden"],
                    method=CausalLMWithILQLHeads.target_qs,
                )
                logits = shift_logits(
                    out["logits"], target_qs, out["vs"], input_ids, last_only
                )
                return {"logits": logits, "cache": out["cache"]}

            sampler = make_sampler(
                sample_apply,
                functools.partial(self.family.init_cache, self.model_config),
                self.gen_config,
                self.query_length,
                with_values=False,
                cache_sharding=self._decode_cache_sharding(),
            )
        bundle_shardings = {
            "params": self.param_shardings,
            "target": self.target_shardings,
        }
        self._sample_jit = jax.jit(
            sampler,
            in_shardings=(bundle_shardings, batch_sh, batch_sh, rep),
            out_shardings=batch_sh,
        )

    # ------------------------------------------------------------------ #

    def sample(self, prompt_ids, prompt_mask):
        self.rng, key = jax.random.split(self.rng)
        return self._sample_jit(
            self.rollout_bundle(),
            prompt_ids,
            prompt_mask,
            key,
        )

    @property
    def eval_batch_size(self) -> int:
        return self.config.train.batch_size

    def learn(self) -> Dict[str, Any]:
        """Offline optimization loop (reference `accelerate_base_model.py
        :224-305` without experience refresh)."""
        train = self.config.train
        if self.store is None:
            raise ValueError("no offline data: run OfflineOrchestrator.make_experience")

        # resume (reference Ray session restore, `accelerate_base_model.py:
        # 232-240`)
        if train.resume_from_checkpoint and has_checkpoint(train.checkpoint_dir):
            self.load(train.checkpoint_dir)

        n_minibatches = max(len(self.store) // train.batch_size, 1)
        total_steps = min(train.total_steps, train.epochs * n_minibatches)

        logger = Logger(
            project_name=train.project_name,
            run_name=train.run_name,
            config=self.config.to_dict(),
            tags=train.tags,
            total_steps=total_steps,
        )
        self.logger = logger
        try:
            result = self._learn_body(logger, total_steps, n_minibatches)
        except BaseException as e:
            # crash forensics (telemetry/flight_recorder.py): no-op when
            # health is off, at most one dump per run
            self.flight_dump_on_exception(e)
            # run ledger (telemetry/run_ledger.py): the failed-run
            # manifest records the error outcome
            self.append_run_ledger(status="error", error=e)
            raise
        else:
            self.append_run_ledger(status="ok")
            return result
        finally:
            # single epilogue for every exit (incl. exceptions): join
            # in-flight async checkpoint writes, close the logger even if
            # that join raises
            try:
                wait_for_checkpoints()
            finally:
                logger.finish()

    def _learn_body(
        self, logger: Logger, total_steps: int, n_minibatches: int
    ) -> Dict[str, Any]:
        train = self.config.train
        stats = self.evaluate()
        logger.log(stats, step=0)

        clock = Clock()
        self._chunk_index = -1  # flight-recorder "phase" = fused chunk
        iter_count = int(self.state.step)  # nonzero after resume
        if iter_count >= total_steps:
            self._final_stats = {}
            return {}
        final_stats: Dict[str, Any] = {}
        # Chunked fused loop: consecutive updates up to the next eval/save
        # boundary (or total_steps) run as one scanned dispatch; per-step log
        # rows are replayed from the stacked stats, so cadence matches the
        # stepwise loop exactly.
        MAX_CHUNK = 32

        def next_chunk_len(step: int, remaining_mbs: int) -> int:
            k = min(MAX_CHUNK, remaining_mbs, total_steps - step)
            for boundary in (train.eval_interval, train.checkpoint_interval):
                to_boundary = boundary - (step % boundary)
                k = min(k, to_boundary)
            return max(k, 1)

        # Resume alignment (docs/resilience.md): a run resumed at step s
        # continues the SAME epoch/minibatch schedule the uninterrupted
        # run would — epoch s // n_minibatches, at minibatch
        # s % n_minibatches of that epoch's seeded order — instead of
        # retraining the early epochs and never reaching the schedule's
        # tail before total_steps cuts the run off.
        epoch0 = iter_count // n_minibatches
        row0 = iter_count % n_minibatches
        for epoch in range(epoch0, train.epochs):
            order = self.store.epoch_order(
                train.batch_size, shuffle=True, seed=train.seed + epoch
            )
            row = row0 if epoch == epoch0 else 0
            while row < len(order):
                k = next_chunk_len(iter_count, len(order) - row)
                mbs = self.store.stacked_slice(
                    order[row : row + k], sharding=self._stacked_batch_sh
                )
                row += k
                # free the compute-dtype sampler bundle through the train
                # chunk (memory high-water mark); eval recasts lazily
                self._rollout_bundle_cache = None
                self.state, stacked = self._train_chunk_jit(self.state, mbs)
                chunk_time = clock.tick(train.batch_size) / 1000.0
                # one transfer event for the whole stacked stats tree AND
                # the step counter — save() reuses the fetched step instead
                # of paying its own device_get round-trip
                rows, host_step = jax.device_get((stacked, self.state.step))
                self._chunk_index += 1
                if self.health_monitor is not None:
                    # every fetched chunk row feeds the detectors — the
                    # batched transfer above already paid; one flight
                    # record per chunk (the ILQL "phase"). BEFORE
                    # check_anomalies: a NaN chunk must reach the
                    # nan-precursor trip + flight ring before the
                    # anomaly abort raises
                    hrow = self.observe_health_rows(
                        rows, step0=iter_count, phase=self._chunk_index
                    )
                    self.record_flight_phase(
                        self._chunk_index, step=iter_count + k,
                        stats_row=hrow,
                    )
                self.check_anomalies(rows, iter_count)
                for j in range(k):
                    iter_count += 1
                    step_stats = {key: float(v[j]) for key, v in rows.items()}
                    step_stats["time/batch"] = chunk_time / k
                    if iter_count % train.log_interval == 0:
                        logger.log(step_stats, step=iter_count)
                        final_stats = dict(step_stats)
                iv = self.intervals(iter_count)
                if iv["do_eval"] and iter_count < total_steps:
                    eval_stats = self.evaluate()
                    logger.log(eval_stats, step=iter_count)
                    final_stats.update(eval_stats)
                if iv["do_save"] and iter_count < total_steps:
                    self.save(step=int(host_step))
                if iter_count >= total_steps:
                    self.save(step=int(host_step))
                    eval_stats = self.evaluate()
                    logger.log(eval_stats, step=iter_count)
                    final_stats.update(eval_stats)
                    self._final_stats = final_stats
                    return final_stats
                # preemption drain point (docs/resilience.md): the ILQL
                # "phase boundary" is the fused chunk — emergency
                # checkpoint + PreemptionDrain before the next dispatch
                self.maybe_drain(phase=self._chunk_index, step=iter_count)
        self._final_stats = final_stats
        return final_stats

    def save(
        self, directory: Optional[str] = None, step: Optional[int] = None
    ) -> None:
        """``step`` lets the train loop reuse its already-fetched counter
        (batched with the stats transfer) instead of a second round-trip."""
        if step is None:
            step = int(jax.device_get(self.state.step))
        save_checkpoint(
            directory or self.config.train.checkpoint_dir,
            self.state,
            metadata=self._save_metadata(),
            async_save=self.config.train.async_checkpoint,
            step=step,
        )

    def _save_metadata(self) -> Dict[str, Any]:
        """Host-metadata pytree (JSON-safe; see the resume auditor's
        ``state_manifest`` lock)."""
        return {
            # sample() splits self.rng per call: without carrying the
            # chain, a resumed run's post-resume samples would replay
            # the seed-time keys and diverge from the uninterrupted run
            # (the resume-state gap engine 15's differ pins)
            "rng_key": np.asarray(jax.device_get(self.rng))
            .ravel()
            .tolist(),
            "host_state": self.host_state_dict(),
        }

    def load(self, directory: str) -> None:
        abstract = jax.tree_util.tree_map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            self.state,
            self.state_shardings,
        )
        self.state, meta = load_checkpoint(directory, abstract)
        rng_key = meta.get("rng_key")
        if rng_key is not None:
            self.rng = jnp.asarray(
                np.asarray(rng_key, dtype=np.uint32).reshape(
                    np.shape(self.rng)
                )
            )
        self.load_host_state_dict(meta.get("host_state") or {})
