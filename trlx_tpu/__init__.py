"""trlx_tpu — TPU-native RLHF framework.

Brand-new JAX/XLA/pjit implementation of the capabilities of
danyang-rainbow/trlx-t5 (trlX v0.3.0 + T5/UL2 seq2seq PPO fork): online PPO
against a user reward function, offline ILQL on reward-labeled datasets, for
causal LMs (GPT-2 family) and T5/UL2 seq2seq models, sharded over a TPU mesh.
"""

# single source of truth is pyproject.toml; fall back when not installed
try:
    from importlib.metadata import version as _pkg_version

    __version__ = _pkg_version("trlx_tpu")
except Exception:
    __version__ = "0.3.0"  # tracks the reference's trlX version (setup.cfg:1-8)

from trlx_tpu.api import train  # noqa: E402,F401
from trlx_tpu.data.configs import TRLConfig  # noqa: E402,F401
