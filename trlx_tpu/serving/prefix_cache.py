"""Host-side shared-prefix block pool: radix trie + refcounts.

The allocator behind cross-request prefix/KV reuse (docs/serving.md).
Device storage is the per-layer ``shared_k``/``shared_v`` pool the
engine carries when built with ``prefix_pool_blocks > 0``
(``inference/kv_cache.py``); this module decides **which** pool block
holds **which** prefix content, and for every admitted request builds
the per-row ``shared_map`` / ``publish_map`` the prefill consumes.

Correctness contract (why sharing is *exact*): a padded prompt column's
K/V depends only on the leading columns' ``(token, mask)`` pairs —
causal attention bounds the ids, and the position ids are a cumsum of
the leading mask. The trie therefore keys each block on the exact
``(ids, mask)`` content of its ``block_size`` columns, and a request
may share block ``j`` only when blocks ``0..j`` all match — identical
leading columns ⇒ bitwise-identical K/V, and the engine's read side is
a pure gather. Left-padded prompts share iff they pad identically
(in practice: equal prompt lengths with a common leading prefix — the
parity caveat documented in docs/serving.md).

Lifecycle per pool block:

- **publish**: first request with an unseen prefix block allocates a
  free pool block (``publish_map[j] = block``), its prefill scatters
  the bits in, and the block flips ``ready`` once that prefill has been
  dispatched (:meth:`mark_ready` from the engine's admit listener —
  dispatch order makes the device write land before any later reader's
  gather).
- **share**: later requests whose leading blocks match a ready chain
  map them read-only (``shared_map[j] = block``) and take a refcount.
- **copy-on-divergent-write**: published blocks are immutable; a
  request whose content diverges inside block ``j`` (or beyond a
  published chain) gets a *fresh* block for the divergent content —
  never an in-place update of a block someone else reads. At block
  granularity, "copy on first divergent write" is exactly this
  allocate-a-sibling move (:func:`test_serving` pins it).
- **release**: refcount drops at request completion; double release
  raises. Refcount-0 leaves are evictable LRU when the pool is full.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


class DoubleFreeError(RuntimeError):
    """A shared block was released more times than it was acquired."""


@dataclass
class _Node:
    """One trie node = one pool block holding one block's columns."""

    key: Tuple
    block_id: int
    parent: Optional["_Node"]
    ready: bool = False
    refcount: int = 0
    tick: int = 0
    children: Dict[Tuple, "_Node"] = field(default_factory=dict)


@dataclass
class AdmissionPlan:
    """Per-request sharing decision: the prefill maps plus the blocks
    this request now holds references on (released at completion)."""

    shared_map: np.ndarray  # [n_blocks] int32, -1 = private
    publish_map: np.ndarray  # [n_blocks] int32, -1 = no publish
    acquired: List[int]  # pool blocks refcounted to this request
    published: List[int]  # subset of acquired pending mark_ready
    hit_blocks: int  # ready blocks reused (true cross-request hits)


class PrefixBlockPool:
    """Refcounted trie allocator over ``pool_blocks`` shared KV blocks."""

    def __init__(self, pool_blocks: int, block_size: int, n_blocks: int):
        if pool_blocks < 1:
            raise ValueError(f"pool_blocks={pool_blocks} must be >= 1")
        self.pool_blocks = int(pool_blocks)
        self.block_size = int(block_size)
        self.n_blocks = int(n_blocks)  # logical blocks per slot
        self._free: List[int] = list(range(self.pool_blocks))
        self._root: Dict[Tuple, _Node] = {}
        self._nodes: Dict[int, _Node] = {}
        self._tick = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # ------------------------------ helpers ---------------------------- #

    def _block_key(self, ids, mask, j: int) -> Tuple:
        bs = self.block_size
        sl = slice(j * bs, (j + 1) * bs)
        return (
            tuple(int(x) for x in ids[sl]),
            tuple(int(x) for x in mask[sl]),
        )

    def _alloc(self) -> Optional[int]:
        if self._free:
            return self._free.pop(0)
        victim = self._evictable()
        if victim is None:
            return None
        self._evict(victim)
        return self._free.pop(0)

    def _evictable(self) -> Optional[_Node]:
        """Oldest refcount-0 leaf (children pin their parents: evicting
        an interior block would orphan a chain someone can still walk)."""
        best = None
        for node in self._nodes.values():
            if node.refcount == 0 and node.ready and not node.children:
                if best is None or node.tick < best.tick:
                    best = node
        return best

    def _remove(self, node: _Node) -> None:
        siblings = (
            node.parent.children if node.parent is not None else self._root
        )
        siblings.pop(node.key, None)
        self._nodes.pop(node.block_id, None)
        self._free.append(node.block_id)

    def _evict(self, node: _Node) -> None:
        self._remove(node)
        self.evictions += 1

    # ------------------------------- API -------------------------------- #

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def plan_admission(
        self, ids, mask, eligible_blocks: Optional[int] = None
    ) -> AdmissionPlan:
        """Sharing decision for one request's padded prompt columns.

        Walks the trie over the leading blocks: ready matches are
        shared (refcount acquired), the first unseen block starts a
        publish chain (fresh pool blocks — divergence NEVER mutates a
        published block), an in-flight (not yet ready) match stops the
        walk (its bits are not readable yet; this request keeps those
        blocks private). ``eligible_blocks`` caps the walk (default:
        every full block that fits the prompt columns).
        """
        ids = np.asarray(ids).reshape(-1)
        mask = np.asarray(mask).reshape(-1)
        n_eligible = (
            min(self.n_blocks, len(ids) // self.block_size)
            if eligible_blocks is None
            else min(eligible_blocks, self.n_blocks)
        )
        shared = np.full((self.n_blocks,), -1, np.int32)
        publish = np.full((self.n_blocks,), -1, np.int32)
        acquired: List[int] = []
        published: List[int] = []
        hit_blocks = 0
        level = self._root
        parent: Optional[_Node] = None
        publishing = False
        self._tick += 1
        for j in range(n_eligible):
            key = self._block_key(ids, mask, j)
            node = level.get(key)
            if node is not None and not publishing:
                if not node.ready:
                    # someone is publishing this very block right now —
                    # its bits are not readable yet; stay private from
                    # here down (no wait states on the admission path)
                    break
                node.refcount += 1
                node.tick = self._tick
                shared[j] = node.block_id
                acquired.append(node.block_id)
                hit_blocks += 1
                parent, level = node, node.children
                continue
            # miss (or divergence below a block we just published):
            # allocate fresh — published blocks are immutable
            block_id = self._alloc()
            if block_id is None:
                break  # pool exhausted: rest stays private
            node = _Node(key=key, block_id=block_id, parent=parent)
            node.refcount = 1
            node.tick = self._tick
            level[key] = node
            self._nodes[block_id] = node
            shared[j] = block_id  # publisher reads its own publish
            publish[j] = block_id
            acquired.append(block_id)
            published.append(block_id)
            publishing = True
            parent, level = node, node.children
        self.hits += hit_blocks
        self.misses += len(published)
        return AdmissionPlan(
            shared_map=shared,
            publish_map=publish,
            acquired=acquired,
            published=published,
            hit_blocks=hit_blocks,
        )

    def mark_ready(self, blocks: Sequence[int]) -> None:
        """Published blocks become readable (their prefill dispatched)."""
        for b in blocks:
            node = self._nodes.get(int(b))
            if node is not None:
                node.ready = True

    def abandon(self, blocks: Sequence[int]) -> None:
        """Roll back a planned admission whose engine submit FAILED:
        drop the plan's references, and remove never-ready published
        nodes entirely — their prefill will never dispatch, so leaving
        them would permanently break the trie walk for that prefix
        (readers stop at a not-ready node) AND pin the pool blocks
        (``_evictable`` skips un-ready nodes). Walks leaf-first so a
        removed child unpins its parent within the same call."""
        for b in reversed(list(blocks)):
            node = self._nodes.get(int(b))
            if node is None:
                continue
            if node.refcount > 0:
                node.refcount -= 1
            if not node.ready and node.refcount == 0 and not node.children:
                self._remove(node)

    def release(self, blocks: Sequence[int]) -> None:
        """Drop one reference per listed block (request completed)."""
        for b in blocks:
            node = self._nodes.get(int(b))
            if node is None or node.refcount < 1:
                raise DoubleFreeError(
                    f"shared prefix block {int(b)} released more times "
                    "than acquired"
                )
            node.refcount -= 1

    def ready_chains(self) -> List[List[int]]:
        """Mask-filtered token sequences of every ready root-to-node
        chain — the speculative drafter's global n-gram corpus
        (``serving/spec_drafter.py``). A chain stops at the first
        not-ready node (its bits are not readable, so its *content* is
        not trustworthy as a draft source either)."""
        out: List[List[int]] = []

        def walk(node: _Node, prefix: List[int]) -> None:
            if not node.ready:
                return
            ids, mask = node.key
            toks = prefix + [int(t) for t, m in zip(ids, mask) if m]
            if toks:
                out.append(toks)
            for child in node.children.values():
                walk(child, toks)

        for node in self._root.values():
            walk(node, [])
        return out

    def stats(self) -> Dict[str, float]:
        total = self.hits + self.misses
        return {
            "prefix_pool/hits": float(self.hits),
            "prefix_pool/misses": float(self.misses),
            "prefix_pool/hit_rate": (self.hits / total) if total else 0.0,
            "prefix_pool/free_blocks": float(self.free_blocks),
            "prefix_pool/evictions": float(self.evictions),
        }
