"""Streaming token output: per-request bounded queues + iterators.

The engine's ``stream_taps`` decode step returns each step's (token,
live) vectors; :class:`StreamRouter` fans them out into per-request
:class:`TokenStream` queues the moment they exist — time-to-first-token
decouples from harvest-group completion (the ``serve/ttft_ms``
histogram measures the difference; docs/serving.md "Streaming").

Host-concurrency contract (engine 14, docs/static_analysis.md): the
single-process serving loop interleaves producer and consumer on one
thread, but a driver-thread + consumer-thread deployment is supported —
so every buffer/flag touch happens under ``TokenStream._lock``. The
close-vs-push handoff is the canonical ``atomicity-split``: ``push``
decides closed-ness and buffers IN ONE critical section (a push racing a
close either lands before it or is dropped and counted, never torn), and
``__next__`` checks buffer-empty and closed under the same lock, so a
token pushed before ``close()`` can never be swallowed by a
``StopIteration``. A full queue drops the OLDEST buffered token and
counts the overflow (``overflows``), never blocks the decode loop.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Iterator, List, Optional

from trlx_tpu.telemetry.tracer import monotonic
from trlx_tpu.utils import sched_points


class TokenStream:
    """Bounded per-request token queue with iterator access.

    ``__next__`` returns buffered tokens first; on an empty buffer it
    calls the ``pump`` callable (one serving-loop iteration) until a
    token lands or the stream closes. Closed + drained ⇒
    ``StopIteration``.
    """

    def __init__(
        self,
        request_id: int,
        maxlen: int = 1024,
        pump: Optional[Callable[[], object]] = None,
    ):
        self.request_id = request_id
        self._buf: "deque[int]" = deque(maxlen=max(1, int(maxlen)))
        self._pump = pump
        # guards every shared field below: producer (push/close from the
        # driver or serving loop) and consumer (__next__/drain) may live
        # on different threads
        self._lock = threading.Lock()
        self.closed = False
        self.overflows = 0  # tokens dropped oldest-first on a full queue
        self.dropped_after_close = 0  # pushes that lost the race to close
        self.emitted = 0
        # stream-delivery trace marks (telemetry/request_trace.py): when
        # the first token reached this queue and when the stream closed
        # — the `serve/stream` span of the request's trace
        self.first_push_at: Optional[float] = None
        self.closed_at: Optional[float] = None

    def push(self, token: int) -> bool:
        """Buffer one token; returns False (token dropped + counted) when
        the stream already closed — closed-ness is decided under the same
        lock as the buffering, so a racing close never tears the pair."""
        sched_points.yield_point("stream.push")
        with self._lock:
            if self.closed:
                self.dropped_after_close += 1
                return False
            if len(self._buf) == self._buf.maxlen:
                self.overflows += 1
            self._buf.append(int(token))
            self.emitted += 1
            if self.first_push_at is None:
                self.first_push_at = monotonic()
            return True

    def close(self) -> None:
        sched_points.yield_point("stream.close")
        with self._lock:
            if not self.closed:
                self.closed_at = monotonic()
            self.closed = True

    def __iter__(self) -> Iterator[int]:
        return self

    def __next__(self) -> int:
        while True:
            sched_points.yield_point("stream.next")
            with self._lock:
                if self._buf:
                    return self._buf.popleft()
                # empty AND closed observed atomically: any token pushed
                # before the close is in the buffer (push holds the same
                # lock), so stopping here cannot lose one
                if self.closed:
                    raise StopIteration
            if self._pump is None:
                raise StopIteration
            if not self._pump():
                if sched_points.instrumented():
                    # the cooperative scheduler serializes progress; a
                    # real sleep would stall the whole schedule
                    continue
                # no progress (e.g. this request is quota-throttled and
                # nothing is decoding): yield the CPU while the bucket
                # refills instead of busy-spinning the serving loop
                time.sleep(0.002)

    def drain(self) -> List[int]:
        """Everything currently buffered, without pumping."""
        with self._lock:
            out = list(self._buf)
            self._buf.clear()
        return out


class StreamRouter:
    """Row-index → :class:`TokenStream` fan-out; the engine's
    ``token_sink``.

    Single-thread contract: the routing table itself (``_streams``) is
    mutated only by the serving loop (attach/close/pop happen at submit
    and harvest, on the loop thread); cross-thread traffic goes through
    the per-stream lock inside :class:`TokenStream`.
    """

    def __init__(self, maxlen: int = 1024):
        self.maxlen = int(maxlen)
        self._streams: Dict[int, TokenStream] = {}

    def attach(self, row: int, stream: TokenStream) -> None:
        """Bind an already-open stream (created at request submit, before
        its engine row existed) to its row."""
        self._streams[row] = stream

    def get(self, row: int) -> Optional[TokenStream]:
        return self._streams.get(row)

    @property
    def active(self) -> int:
        return sum(
            1 for s in self._streams.values() if not s.closed
        )

    def on_tokens(self, emitted: Dict[int, int]) -> None:
        """Engine token-sink callback: ``{row: token}`` for this decode
        step's live emissions. Closed-ness is decided inside
        :meth:`TokenStream.push` (one critical section) — checking
        ``stream.closed`` here first would re-open the check-then-act
        window the per-stream lock exists to close."""
        for row, token in emitted.items():
            stream = self._streams.get(row)
            if stream is not None:
                stream.push(token)

    def close(self, row: int) -> None:
        stream = self._streams.get(row)
        if stream is not None:
            stream.close()

    def pop(self, row: int) -> Optional[TokenStream]:
        return self._streams.pop(row, None)
