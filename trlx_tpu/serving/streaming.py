"""Streaming token output: per-request bounded queues + iterators.

The engine's ``stream_taps`` decode step returns each step's (token,
live) vectors; :class:`StreamRouter` fans them out into per-request
:class:`TokenStream` queues the moment they exist — time-to-first-token
decouples from harvest-group completion (the ``serve/ttft_ms``
histogram measures the difference; docs/serving.md "Streaming").

Single-process contract: the serving loop and the consumer interleave
on one thread (the iterator *pumps the engine* when its queue is
empty), so a ``stream=True`` submit works without any background
machinery. The queues are still thread-safe deques, so a
driver-thread + consumer-thread deployment works unchanged — a full
queue drops the OLDEST buffered token and counts the overflow
(``overflows`` on the stream), never blocks the decode loop.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, Iterator, List, Optional

from trlx_tpu.telemetry.tracer import monotonic


class TokenStream:
    """Bounded per-request token queue with iterator access.

    ``__next__`` returns buffered tokens first; on an empty buffer it
    calls the ``pump`` callable (one serving-loop iteration) until a
    token lands or the stream closes. Closed + drained ⇒
    ``StopIteration``.
    """

    def __init__(
        self,
        request_id: int,
        maxlen: int = 1024,
        pump: Optional[Callable[[], object]] = None,
    ):
        self.request_id = request_id
        self._buf: "deque[int]" = deque(maxlen=max(1, int(maxlen)))
        self._pump = pump
        self.closed = False
        self.overflows = 0  # tokens dropped oldest-first on a full queue
        self.emitted = 0
        # stream-delivery trace marks (telemetry/request_trace.py): when
        # the first token reached this queue and when the stream closed
        # — the `serve/stream` span of the request's trace
        self.first_push_at: Optional[float] = None
        self.closed_at: Optional[float] = None

    def push(self, token: int) -> None:
        if len(self._buf) == self._buf.maxlen:
            self.overflows += 1
        self._buf.append(int(token))
        self.emitted += 1
        if self.first_push_at is None:
            self.first_push_at = monotonic()

    def close(self) -> None:
        if not self.closed:
            self.closed_at = monotonic()
        self.closed = True

    def __iter__(self) -> Iterator[int]:
        return self

    def __next__(self) -> int:
        while True:
            if self._buf:
                return self._buf.popleft()
            if self.closed:
                raise StopIteration
            if self._pump is None:
                raise StopIteration
            if not self._pump():
                # no progress (e.g. this request is quota-throttled and
                # nothing is decoding): yield the CPU while the bucket
                # refills instead of busy-spinning the serving loop
                time.sleep(0.002)

    def drain(self) -> List[int]:
        """Everything currently buffered, without pumping."""
        out = list(self._buf)
        self._buf.clear()
        return out


class StreamRouter:
    """Row-index → :class:`TokenStream` fan-out; the engine's
    ``token_sink``."""

    def __init__(self, maxlen: int = 1024):
        self.maxlen = int(maxlen)
        self._streams: Dict[int, TokenStream] = {}

    def attach(self, row: int, stream: TokenStream) -> None:
        """Bind an already-open stream (created at request submit, before
        its engine row existed) to its row."""
        self._streams[row] = stream

    def get(self, row: int) -> Optional[TokenStream]:
        return self._streams.get(row)

    @property
    def active(self) -> int:
        return sum(
            1 for s in self._streams.values() if not s.closed
        )

    def on_tokens(self, emitted: Dict[int, int]) -> None:
        """Engine token-sink callback: ``{row: token}`` for this decode
        step's live emissions."""
        for row, token in emitted.items():
            stream = self._streams.get(row)
            if stream is not None and not stream.closed:
                stream.push(token)

    def close(self, row: int) -> None:
        stream = self._streams.get(row)
        if stream is not None:
            stream.close()

    def pop(self, row: int) -> Optional[TokenStream]:
        return self._streams.pop(row, None)
