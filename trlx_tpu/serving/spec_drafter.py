"""Host-side draft proposers for speculative decoding.

The drafting half of the engine's drafted ``verify_step``
(docs/inference.md "Speculative decoding"): a drafter proposes up to
``max_draft`` next tokens per row from *host-visible* state only — no
extra device forward, no draft model — and the jitted verify step
accepts the longest prefix whose tokens bitwise-match what the target
policy's ``choose_tokens`` samples under the per-row
``fold_in(row_key, t)`` keys. A wrong draft therefore costs padded
verify FLOPs, never correctness, which is what lets the drafters here
be cheap heuristics:

- :class:`NGramDrafter` — prompt-lookup decoding: the longest suffix of
  a row's own history (prompt + committed emissions) that recurred
  earlier in that same history predicts its previous continuation. Free,
  per-row, and strong exactly where RLHF rollouts repeat themselves
  (quotes from the prompt, templated spans).
- :class:`TrieDrafter` — the n-gram fallback plus a *global* corpus: the
  ready chains of the PR-13 shared-prefix radix trie
  (:meth:`~trlx_tpu.serving.prefix_cache.PrefixBlockPool.ready_chains`).
  Rows that diverged from a shared prefix early still draft from what
  the fleet's other requests already published — the
  "system-integrated" drafter shape of ROADMAP direction 2b.

Accept-rate adaptivity: every proposer keeps a per-tenant EWMA of the
verify step's accept fraction (rows map to tenants via
:meth:`set_tenant`; unmapped rows are their own tenant). When the EWMA
sinks below ``min_accept_ewma`` the drafter returns empty drafts for
that tenant and the engine's ``_step_once`` falls through to plain
one-token decode — graceful degrade, never an abort. The EWMA keeps
updating from later verify outcomes only via fresh probes: after
``DEGRADE_PROBE_EVERY`` suppressed draws the drafter emits one probe
draft so a tenant whose text became predictable again can climb back
out.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

__all__ = ["NGramDrafter", "TrieDrafter"]

# One probe draft per this many suppressed draws keeps a degraded
# tenant's EWMA live (pure suppression would freeze it below the bar
# forever).
DEGRADE_PROBE_EVERY = 16


class NGramDrafter:
    """Per-row suffix n-gram self-lookup (prompt-lookup decoding).

    :param max_draft: proposal cap per draw (the engine clamps its own
        ``spec_max_draft`` the same way; the shorter wins).
    :param max_ngram: longest suffix tried as the lookup needle; longer
        matches win (tried first), down to ``min_ngram``.
    :param min_ngram: shortest needle worth matching — 1-gram lookup is
        near-noise on real vocabularies, so the default floor is 2.
    :param min_accept_ewma: accept-rate floor; a tenant whose EWMA sinks
        below it stops drafting (modulo probes). 0 never degrades.
    :param ewma_alpha: EWMA step for each verify outcome.
    """

    def __init__(
        self,
        max_draft: int = 4,
        max_ngram: int = 4,
        min_ngram: int = 2,
        min_accept_ewma: float = 0.0,
        ewma_alpha: float = 0.2,
    ):
        if max_draft < 1:
            raise ValueError(f"max_draft={max_draft} must be >= 1")
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"min_ngram={min_ngram} max_ngram={max_ngram}"
            )
        if not 0.0 <= min_accept_ewma <= 1.0:
            raise ValueError(
                f"min_accept_ewma={min_accept_ewma} must be in [0, 1]"
            )
        if not 0.0 < ewma_alpha <= 1.0:
            raise ValueError(
                f"ewma_alpha={ewma_alpha} must be in (0, 1]"
            )
        self.max_draft = int(max_draft)
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        self.min_accept_ewma = float(min_accept_ewma)
        self.ewma_alpha = float(ewma_alpha)
        self._hist: Dict[int, List[int]] = {}
        self._tenant: Dict[int, str] = {}
        # EWMA starts at 1.0: a fresh tenant drafts until evidence says
        # otherwise (starting below the bar would deadlock degrade-off)
        self._ewma: Dict[str, float] = {}
        self._suppressed: Dict[str, int] = {}
        self.drafts = 0
        self.draft_hits = 0
        self.degraded_draws = 0

    # --------------------------- row lifecycle -------------------------- #

    def observe_context(self, row: int, tokens: Sequence[int]) -> None:
        """Seed a freshly admitted row's history with its (unpadded)
        prompt tokens."""
        self._hist[row] = [int(t) for t in tokens]

    def observe_tokens(self, row: int, tokens: Sequence[int]) -> None:
        """Append committed emissions (decode-tap or accepted verify
        columns) to the row's history."""
        self._hist.setdefault(row, []).extend(int(t) for t in tokens)

    def observe_accept(
        self, row: int, n_proposed: int, n_accepted: int
    ) -> None:
        """Fold one verify outcome into the row's tenant EWMA."""
        if n_proposed < 1:
            return
        tenant = self._tenant.get(row, f"row:{row}")
        rate = n_accepted / n_proposed
        prev = self._ewma.get(tenant, 1.0)
        self._ewma[tenant] = (
            self.ewma_alpha * rate + (1.0 - self.ewma_alpha) * prev
        )

    def set_tenant(self, row: int, tenant: Optional[str]) -> None:
        """Map a row to a tenant for accept-rate accounting (rows of
        one tenant share text statistics; unmapped rows degrade
        independently)."""
        if tenant is None:
            self._tenant.pop(row, None)
        else:
            self._tenant[row] = str(tenant)

    def forget(self, row: int) -> None:
        """Drop a harvested row's history (its slot is being reused)."""
        self._hist.pop(row, None)
        self._tenant.pop(row, None)

    def reset(self) -> None:
        """Drop all row state (phase boundary). Tenant EWMAs persist —
        accept statistics are a property of the tenant's text, not of
        one phase's slot assignments."""
        self._hist.clear()
        self._tenant.clear()

    # --------------------------- checkpointing --------------------------- #

    def state_dict(self) -> Dict[str, object]:
        """Resume-carried drafter state: the per-tenant accept EWMAs and
        probe counters *feed the drafting schedule* (they decide whether
        a tenant drafts at all), so a resumed run must see the same
        values the killed run had — resetting them to 1.0 would re-draft
        for a degraded tenant and diverge from the uninterrupted twin.
        Row histories/tenant maps are phase-scoped (``reset()`` drops
        them at every phase boundary) and telemetry counters are
        parity-inert, so neither is carried."""
        return {
            "ewma": dict(self._ewma),
            "suppressed": dict(self._suppressed),
        }

    def load_state_dict(self, state: Dict[str, object]) -> None:
        self._ewma = {str(k): float(v) for k, v in state["ewma"].items()}
        self._suppressed = {
            str(k): int(v) for k, v in state["suppressed"].items()
        }

    # ------------------------------ drafting ---------------------------- #

    def accept_ewma(self, tenant: str) -> float:
        return self._ewma.get(tenant, 1.0)

    def _degraded(self, row: int) -> bool:
        """True when this draw should be suppressed for accept-rate
        degrade (counts a probe allowance so the EWMA stays live)."""
        if self.min_accept_ewma <= 0.0:
            return False
        tenant = self._tenant.get(row, f"row:{row}")
        if self._ewma.get(tenant, 1.0) >= self.min_accept_ewma:
            self._suppressed.pop(tenant, None)
            return False
        n = self._suppressed.get(tenant, 0) + 1
        if n >= DEGRADE_PROBE_EVERY:
            self._suppressed[tenant] = 0
            return False  # probe: one draft to refresh the EWMA
        self._suppressed[tenant] = n
        self.degraded_draws += 1
        return True

    def _lookup(
        self, hist: Sequence[int], corpus: Sequence[int]
    ) -> List[int]:
        """Longest-suffix n-gram match of ``hist`` inside ``corpus``,
        returning the continuation after the *most recent* match. When
        ``corpus is hist`` the terminal occurrence (the needle itself)
        is excluded."""
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if len(hist) < n:
                continue
            needle = list(hist[-n:])
            limit = len(corpus) - n - (1 if corpus is hist else 0)
            for i in range(limit, -1, -1):
                if list(corpus[i : i + n]) == needle:
                    cont = list(corpus[i + n : i + n + self.max_draft])
                    if cont:
                        return cont
        return []

    def draft(self, row: int) -> List[int]:
        """Up to ``max_draft`` proposed next tokens for ``row`` ([] =
        no proposal; the engine falls through to one-token decode)."""
        if self._degraded(row):
            return []
        hist = self._hist.get(row)
        if not hist:
            return []
        self.drafts += 1
        out = self._lookup(hist, hist)
        if out:
            self.draft_hits += 1
        return out

    def stats(self) -> Dict[str, float]:
        return {
            "spec_drafter/draws": float(self.drafts),
            "spec_drafter/hits": float(self.draft_hits),
            "spec_drafter/degraded_draws": float(self.degraded_draws),
        }


class TrieDrafter(NGramDrafter):
    """N-gram drafting backed by the shared-prefix trie's published
    chains as a global corpus, with the per-row self-lookup of
    :class:`NGramDrafter` as first preference (a row's own history is
    the best predictor of its own continuation; the trie catches rows
    whose history hasn't repeated yet but whose prompt family has).

    :param pool: the engine's :class:`PrefixBlockPool`; ``None`` keeps
        pure n-gram behavior (the sharing-off serving build).
    """

    def __init__(self, pool=None, **kwargs):
        super().__init__(**kwargs)
        self.pool = pool
        self.trie_hits = 0

    def draft(self, row: int) -> List[int]:
        if self._degraded(row):
            return []
        hist = self._hist.get(row)
        if not hist:
            return []
        self.drafts += 1
        out = self._lookup(hist, hist)
        if out:
            self.draft_hits += 1
            return out
        if self.pool is not None:
            # chains extend their parents, so several ready chains can
            # match the same suffix with continuations of different
            # depth — the longest proposal wins (acceptance truncates
            # at the first mismatch anyway; length costs nothing extra)
            best: List[int] = []
            for chain in self.pool.ready_chains():
                cand = self._lookup(hist, chain)
                if len(cand) > len(best):
                    best = cand
            if best:
                self.draft_hits += 1
                self.trie_hits += 1
                return best
        return []

    def stats(self) -> Dict[str, float]:
        out = super().stats()
        out["spec_drafter/trie_hits"] = float(self.trie_hits)
        return out
