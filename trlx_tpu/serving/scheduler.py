"""QoS request scheduler: per-tenant queues, quotas, SLO-aware order.

The serving tier's admission brain (docs/serving.md). The continuous
engine exposes a pool of decode slots; every time slots vacate the
serving loop asks :meth:`QoSScheduler.next_batch` which queued requests
feed them. The decision combines, in order of force:

- **quota** — a per-tenant token bucket (``rate`` tokens/s refill,
  ``burst`` cap) charged at admission with the request's estimated
  token cost (prompt + generation budget). An exhausted tenant is
  *throttled, not starved*: its requests stay queued and the bucket
  refills with wall time, so they admit as soon as the quota allows.
  Aging never overrides quota (a noisy neighbor cannot age its way
  past its contract).
- **effective priority** — the request's static priority plus an aging
  term (``queue_wait / aging_half_ms`` points), so low-priority
  requests cannot starve behind a steady high-priority stream: wait
  long enough and any request outranks a fresh one.
- **SLO pressure** — the scheduler reads the per-tenant
  ``serve/queue_wait_ms[tenant=...]`` histograms (PR-12's measurement
  layer) and boosts tenants whose recent p95 approaches their SLO
  class's queue-wait budget — the feedback loop that turns the
  histograms into scheduling decisions.
- **deadline** — ties break earliest-deadline-first, then submission
  order (deterministic: equal inputs give an identical order, which
  the unit tests pin).

Host-only, stdlib + the metrics registry; no jax at import time.
"""

from __future__ import annotations

import heapq
import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from trlx_tpu.telemetry.tracer import monotonic

#: the tenant unknown submitters land under: unmetered, priority 0
DEFAULT_TENANT = "default"


def tenant_metric_key(base: str, tenant: str) -> str:
    """Per-tenant histogram name: ``serve/queue_wait_ms[tenant=acme]``.
    One flat key per (metric, tenant) — the registry stays a plain
    namespace and ``--compare`` diffs tenants like any other series."""
    return f"{base}[tenant={tenant}]"


@dataclass(frozen=True)
class SLOClass:
    """A latency contract: requests of this class should spend at most
    ``queue_wait_budget_ms`` (p95) waiting for a slot. The `slo-breach`
    health detector trips when the measured ratio exceeds 1."""

    name: str
    queue_wait_budget_ms: float


DEFAULT_SLO_CLASSES: Dict[str, SLOClass] = {
    "interactive": SLOClass("interactive", 200.0),
    "standard": SLOClass("standard", 2_000.0),
    "batch": SLOClass("batch", 30_000.0),
}


@dataclass
class TokenBucket:
    """Classic token bucket; time injected for determinism (tests drive
    a fake clock, production passes the shared telemetry clock)."""

    rate: float  # tokens per second
    burst: float  # bucket capacity
    level: float = field(default=-1.0)
    last_refill: float = field(default=-1.0)

    def __post_init__(self):
        if self.level < 0:
            self.level = self.burst

    def refill(self, now: float) -> None:
        if self.last_refill < 0:
            self.last_refill = now
            return
        dt = max(0.0, now - self.last_refill)
        self.level = min(self.burst, self.level + dt * self.rate)
        self.last_refill = now

    def try_charge(self, cost: float, now: float) -> bool:
        self.refill(now)
        if self.level + 1e-9 < cost:
            return False
        self.level -= cost
        return True

    def state_dict(self) -> Dict[str, float]:
        """Resume-carried quota state. Only ``level`` travels: the
        refill anchor is a *monotonic* timestamp that does not survive
        a process restart, so restoring it raw would either grant a
        huge spurious refill (new clock ahead) or freeze refills (new
        clock behind). Dropping it back to the -1 sentinel makes the
        first post-restore ``refill`` re-anchor without adding credit —
        the drained-tenant throttle the level encodes carries across
        the kill, which is the part that feeds the admission schedule."""
        return {"level": float(self.level)}

    def load_state_dict(self, state: Dict[str, float]) -> None:
        self.level = min(float(state["level"]), self.burst)
        self.last_refill = -1.0


@dataclass(frozen=True)
class TenantConfig:
    """Per-tenant admission contract (``train.serving.tenants.<name>``)."""

    name: str
    priority: int = 0
    rate: float = math.inf  # quota refill, tokens/second
    burst: float = math.inf  # quota burst capacity, tokens
    slo_class: str = "standard"

    @classmethod
    def from_dict(cls, name: str, d: Dict[str, Any]) -> "TenantConfig":
        known = {"priority", "rate", "burst", "slo_class"}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"Unknown serving.tenants[{name!r}] keys: "
                f"{sorted(unknown)} (known: {sorted(known)})"
            )
        cfg = cls(name=name, **d)
        if cfg.rate <= 0 and not math.isinf(cfg.burst):
            raise ValueError(
                f"serving.tenants[{name!r}]: rate={cfg.rate} with a "
                f"finite burst={cfg.burst} — a drained bucket would "
                "never refill, so the tenant would hang forever instead "
                "of throttling; use rate > 0 (or leave both unset for "
                "an unmetered tenant)"
            )
        return cfg


@dataclass
class Request:
    """One typed serving request. ``cost`` (estimated tokens: real
    prompt length + generation budget) is what the tenant's bucket is
    charged; ``deadline`` is absolute on the scheduler's clock."""

    request_id: int
    tenant: str
    prompt_ids: Any  # [Q] int32 left-padded host array
    prompt_mask: Any  # [Q] int32
    priority: int = 0
    slo_class: str = "standard"
    max_tokens: int = 0
    deadline: Optional[float] = None
    stream: bool = False
    cost: float = 0.0
    submitted_at: float = 0.0
    seq: int = 0  # global submission order (final tie-break)
    # distributed-tracing marks (telemetry/request_trace.py): the
    # trace_id minted at InferenceServer.submit, the first time the
    # request was skipped because its tenant's quota was exhausted
    # (the quota-hold stage starts here), and the pick time (scheduler
    # → engine handoff). Host floats on the shared telemetry clock.
    trace_id: str = ""
    quota_blocked_at: Optional[float] = None
    picked_at: float = 0.0


class QoSScheduler:
    """Per-tenant queues + the admission policy described in the module
    docstring. Single-threaded like the engine's host loop."""

    def __init__(
        self,
        tenants: Optional[Dict[str, TenantConfig]] = None,
        slo_classes: Optional[Dict[str, SLOClass]] = None,
        aging_half_ms: float = 1000.0,
        clock: Callable[[], float] = monotonic,
        registry=None,
    ):
        self.tenants: Dict[str, TenantConfig] = dict(tenants or {})
        self.slo_classes = dict(DEFAULT_SLO_CLASSES)
        self.slo_classes.update(slo_classes or {})
        self.aging_half_ms = float(aging_half_ms)
        self.clock = clock
        self.registry = registry
        self._queues: Dict[str, List[Request]] = {}
        self._buckets: Dict[str, TokenBucket] = {}
        # plain int (not itertools.count) so the submission-order
        # tie-break survives checkpoint/resume via state_dict()
        self._seq = 0
        self.admitted = 0
        self.throttled_rounds = 0  # quota skips (observability)

    # ------------------------------ intake ----------------------------- #

    def tenant_config(self, tenant: str) -> TenantConfig:
        cfg = self.tenants.get(tenant)
        if cfg is None:
            cfg = TenantConfig(name=tenant)
            self.tenants[tenant] = cfg
        return cfg

    def _bucket(self, tenant: str) -> Optional[TokenBucket]:
        cfg = self.tenant_config(tenant)
        if math.isinf(cfg.rate) and math.isinf(cfg.burst):
            return None  # unmetered
        bucket = self._buckets.get(tenant)
        if bucket is None:
            bucket = self._buckets[tenant] = TokenBucket(
                rate=cfg.rate, burst=cfg.burst
            )
        return bucket

    def validate(self, request: Request) -> None:
        """Raise if ``request`` could never be admitted — WITHOUT
        enqueueing, so a caller can pre-check a whole batch and refuse
        it atomically (a mid-batch refusal after enqueueing would
        orphan the earlier requests)."""
        cfg = self.tenant_config(request.tenant)  # registers unknown tenants
        if request.slo_class not in self.slo_classes:
            raise ValueError(
                f"unknown slo_class {request.slo_class!r} (known: "
                f"{sorted(self.slo_classes)})"
            )
        if request.cost > cfg.burst:
            # a cost the bucket can never hold would queue forever (the
            # level caps at burst) — refuse loudly instead of hanging
            # every flush()/stream() behind an unadmittable request
            raise ValueError(
                f"request cost {request.cost} exceeds tenant "
                f"{request.tenant!r} burst capacity {cfg.burst} — it "
                "could never be admitted; raise the tenant's burst or "
                "shrink the prompt/generation budget"
            )

    def submit(self, request: Request) -> Request:
        """Enqueue; fills scheduler-owned fields (seq, submitted_at,
        defaults inherited from the tenant's config)."""
        self.validate(request)
        request.seq = self._seq
        self._seq += 1
        if request.submitted_at <= 0:
            request.submitted_at = self.clock()
        self._queues.setdefault(request.tenant, []).append(request)
        return request

    @property
    def pending(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def has_work(self) -> bool:
        return any(self._queues.values())

    # --------------------------- checkpointing -------------------------- #

    def state_dict(self) -> Dict[str, Any]:
        """Resume-carried scheduler state: per-tenant bucket levels (a
        drained tenant must stay throttled across the kill), the global
        submission sequence (the final deterministic tie-break — a
        reset would let post-resume requests reorder against any the
        caller re-submits), and the admission counters. Queues are NOT
        carried: the preemption contract drains in-flight requests at
        phase boundaries, so at any checkpointable point they are
        empty; dynamically registered default tenants re-register on
        first touch."""
        return {
            "seq": int(self._seq),
            "admitted": int(self.admitted),
            "throttled_rounds": int(self.throttled_rounds),
            "buckets": {
                tenant: bucket.state_dict()
                for tenant, bucket in sorted(self._buckets.items())
            },
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._seq = int(state["seq"])
        self.admitted = int(state["admitted"])
        self.throttled_rounds = int(state["throttled_rounds"])
        for tenant, bucket_state in state["buckets"].items():
            bucket = self._bucket(tenant)
            if bucket is not None:
                bucket.load_state_dict(bucket_state)

    # ------------------------------ policy ----------------------------- #

    def slo_pressure(self, tenant: str) -> float:
        """Measured queue-wait p95 over the tenant's SLO budget (0 when
        unmeasured) — the histogram-feedback term."""
        ratio = self.slo_ratio(tenant)
        return 0.0 if ratio is None else max(0.0, ratio)

    def slo_ratio(self, tenant: str) -> Optional[float]:
        """p95(serve/queue_wait_ms[tenant]) / class budget, or None
        while the tenant has no completed requests yet."""
        if self.registry is None:
            return None
        hist = self.registry.histogram(
            tenant_metric_key("serve/queue_wait_ms", tenant)
        )
        summary = getattr(hist, "summary", lambda: {"count": 0})()
        if not summary.get("count"):
            return None
        cfg = self.tenant_config(tenant)
        budget = self.slo_classes[cfg.slo_class].queue_wait_budget_ms
        return float(summary["p95"]) / max(budget, 1e-9)

    def effective_priority(
        self,
        request: Request,
        now: float,
        pressure: Optional[float] = None,
    ) -> float:
        """priority + aging + SLO pressure — the admission score.
        ``pressure`` lets :meth:`next_batch` hoist the per-tenant
        histogram read out of the per-request loop (it is constant per
        tenant within one call, and the registry p95 is not free)."""
        wait_ms = max(0.0, (now - request.submitted_at) * 1000.0)
        aging = wait_ms / max(self.aging_half_ms, 1e-9)
        if pressure is None:
            pressure = self.slo_pressure(request.tenant)
        return request.priority + aging + pressure

    def next_batch(
        self, k: int, now: Optional[float] = None
    ) -> List[Request]:
        """Up to ``k`` requests to admit now, best-first. Quota-blocked
        tenants are skipped this round (their requests stay queued);
        everything else orders by (effective priority desc, deadline
        asc, submission seq asc) — deterministically."""
        if k < 1 or not self.has_work():
            return []
        now = self.clock() if now is None else now
        scored = []
        for tenant, queue in self._queues.items():
            if not queue:
                continue
            pressure = self.slo_pressure(tenant)  # one p95 read/tenant
            for req in queue:
                score = self.effective_priority(req, now, pressure)
                deadline = (
                    req.deadline if req.deadline is not None else math.inf
                )
                heapq.heappush(
                    scored, (-score, deadline, req.seq, req)
                )
        picked: List[Request] = []
        blocked: set = set()
        while scored and len(picked) < k:
            _, _, _, req = heapq.heappop(scored)
            if req.tenant in blocked:
                continue
            bucket = self._bucket(req.tenant)
            if bucket is not None and not bucket.try_charge(
                req.cost, now
            ):
                # quota exhausted: the whole tenant waits for refill
                # (in-tenant order is preserved — charging a cheaper
                # later request first would reorder the tenant's FIFO)
                blocked.add(req.tenant)
                self.throttled_rounds += 1
                # quota-hold trace mark: every queued request of the
                # throttled tenant starts (or continues) its hold here
                for held in self._queues[req.tenant]:
                    if held.quota_blocked_at is None:
                        held.quota_blocked_at = now
                continue
            self._queues[req.tenant].remove(req)
            req.picked_at = now
            picked.append(req)
            self.admitted += 1
        return picked

    # --------------------------- observability ------------------------- #

    def queue_depths(self) -> Dict[str, int]:
        return {t: len(q) for t, q in self._queues.items() if q}

    def slo_ratio_rows(self) -> Dict[str, float]:
        """``serve/slo_queue_wait_ratio[tenant=...]`` rows for every
        tenant with measurements — the `slo-breach` detector's feed
        (a ratio > 1 means the tenant's measured queue-wait p95 blew
        its SLO class budget)."""
        out: Dict[str, float] = {}
        for tenant in sorted(self.tenants):
            ratio = self.slo_ratio(tenant)
            if ratio is not None:
                out[
                    tenant_metric_key("serve/slo_queue_wait_ratio", tenant)
                ] = ratio
        return out


def build_scheduler(
    serving_config,
    registry=None,
    clock: Callable[[], float] = monotonic,
) -> QoSScheduler:
    """Scheduler from a :class:`trlx_tpu.serving.ServingConfig`."""
    tenants = {
        name: TenantConfig.from_dict(name, dict(spec))
        for name, spec in (serving_config.tenants or {}).items()
    }
    slo_classes = {
        name: SLOClass(
            name,
            float(
                dict(spec).get(
                    "queue_wait_budget_ms",
                    DEFAULT_SLO_CLASSES.get(
                        name, SLOClass(name, 2_000.0)
                    ).queue_wait_budget_ms,
                )
            ),
        )
        for name, spec in (serving_config.slo_classes or {}).items()
    }
    return QoSScheduler(
        tenants=tenants,
        slo_classes=slo_classes,
        aging_half_ms=serving_config.aging_half_ms,
        clock=clock,
        registry=registry,
    )
