"""Multi-tenant serving tier (docs/serving.md).

The request-level half of the ROADMAP "millions of users" direction
(2b/2c), layered over the continuous-batching engine
(:mod:`trlx_tpu.inference.engine`):

- :mod:`trlx_tpu.serving.scheduler` — typed :class:`Request`s into
  per-tenant queues with token-bucket quotas, priority admission with
  aging (no starvation), deadline/SLO-class ordering that reads the
  ``serve/*`` latency histograms;
- :mod:`trlx_tpu.serving.prefix_cache` — host-side radix trie +
  refcounted shared-block pool: requests with a common prompt prefix
  map their leading KV blocks onto the same published pool blocks
  (``inference/kv_cache.py`` shared-pool layout; read-only sharing,
  copy-on-divergence at block granularity);
- :mod:`trlx_tpu.serving.streaming` — per-request bounded token queues
  fed by the engine's per-decode-step tap, so a ``stream=True`` submit
  returns tokens the step they exist instead of at harvest.

:class:`ServingConfig` parses the ``train.serving`` YAML section (or
the ``serving=`` kwarg of
:class:`~trlx_tpu.inference.server.InferenceServer`, which is rebuilt
on this package).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from trlx_tpu.serving.scheduler import (  # noqa: F401
    DEFAULT_SLO_CLASSES,
    QoSScheduler,
    Request,
    SLOClass,
    TenantConfig,
    TokenBucket,
)
from trlx_tpu.serving.prefix_cache import PrefixBlockPool  # noqa: F401
from trlx_tpu.serving.spec_drafter import (  # noqa: F401
    NGramDrafter,
    TrieDrafter,
)
from trlx_tpu.serving.streaming import (  # noqa: F401
    StreamRouter,
    TokenStream,
)


@dataclass(frozen=True)
class ServingConfig:
    """Parsed ``train.serving`` section.

    :param tenants: per-tenant quota/priority defaults, e.g.
        ``{"gold": {"priority": 10, "rate": 1e9, "burst": 1e9,
        "slo_class": "interactive"}}``. Unknown tenants are admitted
        under :data:`DEFAULT_TENANT` semantics (priority 0, unmetered).
    :param slo_classes: per-class queue-wait budgets overriding
        :data:`~trlx_tpu.serving.scheduler.DEFAULT_SLO_CLASSES`, e.g.
        ``{"interactive": {"queue_wait_budget_ms": 200}}``.
    :param prefix_cache_blocks: shared-prefix pool size in KV blocks;
        0 disables cross-request prefix sharing (and keeps the engine's
        jitted programs byte-identical to the pool-less build).
    :param stream_buffer: per-request streamed-token queue bound.
    :param aging_half_ms: queue wait that buys one effective-priority
        point (anti-starvation aging).
    """

    tenants: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    slo_classes: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    prefix_cache_blocks: int = 0
    stream_buffer: int = 1024
    aging_half_ms: float = 1000.0

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ServingConfig":
        d = dict(d or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"Unknown train.serving keys: {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        for name in ("prefix_cache_blocks", "stream_buffer"):
            if name in d and d[name] is not None:
                d[name] = int(d[name])
        return cls(**d)


__all__ = [
    "DEFAULT_SLO_CLASSES",
    "NGramDrafter",
    "PrefixBlockPool",
    "QoSScheduler",
    "Request",
    "SLOClass",
    "ServingConfig",
    "StreamRouter",
    "TenantConfig",
    "TokenBucket",
    "TokenStream",
    "TrieDrafter",
]
