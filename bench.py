"""Benchmark: PPO samples/sec/chip on the BASELINE workload shape.

Workload (BASELINE.md): gpt2-small policy (124M, bf16), query length 64,
128-token... 48-token rollouts (reference test_config: gen len 48, batch 16,
128 rollouts/phase, 4 ppo_epochs). One full PPO phase = collect 128 rollouts
(compiled sampler + reward + KL penalty vs frozen ref) + 32 optimizer steps
(8 minibatches x 4 ppo_epochs). Weights are randomly initialized (zero-egress
environment: no HF downloads) — identical compute to the pretrained model.

The reference publishes no numbers (BASELINE.md); ``vs_baseline`` is
computed against a documented single-A100 estimate for torch trlX on this
workload (HF generate rollouts + DDP updates): ~12 samples/s.

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

A100_BASELINE_SAMPLES_PER_SEC = 12.0

def main():
    import numpy as np

    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.utils.loading import get_orchestrator, get_pipeline, get_trainer

    os.environ.setdefault("WANDB_DISABLED", "1")

    config = TRLConfig.from_dict(
        {
            "model": {
                "model_type": "gpt2",
                "model_arch": {
                    "vocab_size": 50257,
                    "n_positions": 1024,
                    "n_embd": 768,
                    "n_layer": 12,
                    "n_head": 12,
                },
            },
            "train": {
                "seq_length": 64,
                "batch_size": 16,
                "epochs": 3,
                "total_steps": 10000,
                "eval_interval": 100000,
                "checkpoint_interval": 1000000,
                "mesh": {"dp": -1, "fsdp": 1, "tp": 1},
                "dtype": "bfloat16",
            },
            "method": {
                "name": "PPOConfig",
                "num_rollouts": 128,
                "chunk_size": 128,
                "ppo_epochs": 4,
                "init_kl_coef": 0.05,
                "scale_reward": "running",
                "gen_kwargs": {
                    "max_new_tokens": 48,
                    # fixed-length rollouts, as the reference workload
                    # (ppo_config.yml: min_length == max_length)
                    "min_new_tokens": 48,
                    "top_k": 0,
                    "do_sample": True,
                    "eos_token_id": 50256,
                    "pad_token_id": 50256,
                },
            },
        }
    )

    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(100, 40000, size=rng.integers(4, 33)))
               for _ in range(512)]

    def reward_fn(samples, queries, response_gt=None):
        # cheap host reward: length-normalized char diversity
        return [len(set(s)) / max(len(s), 1) for s in samples]

    trainer = get_trainer(config.train.trainer)(config, reward_fn=reward_fn)
    pipeline = get_pipeline(config.train.pipeline)(
        prompts, config.train.seq_length
    )
    orch = get_orchestrator(config.train.orchestrator)(
        trainer, pipeline, reward_fn=reward_fn, chunk_size=config.method.chunk_size
    )

    def one_phase():
        trainer.buffer.clear_history()
        orch.make_experience(config.method.num_rollouts, 0)
        # one fused dispatch for all minibatch x ppo_epoch updates
        trainer.train_on_buffer()
        import jax

        jax.block_until_ready(trainer.state.params)

    one_phase()  # warmup: compile sampler + fused train phase
    one_phase()  # second warmup: absorbs any donated-buffer relayout retrace

    n_phases = 5
    start = time.time()
    for _ in range(n_phases):
        one_phase()
    elapsed = time.time() - start

    import jax

    n_chips = len(jax.devices())
    samples_per_sec = n_phases * config.method.num_rollouts / elapsed
    per_chip = samples_per_sec / n_chips

    print(
        json.dumps(
            {
                "metric": "ppo_samples_per_sec_per_chip_gpt2s",
                "value": round(per_chip, 3),
                "unit": "samples/s/chip",
                "vs_baseline": round(per_chip / A100_BASELINE_SAMPLES_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
