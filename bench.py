"""Benchmark: PPO samples/sec/chip on the BASELINE workload shape.

Workload (BASELINE.md): gpt2-small policy (124M, bf16), query length 64,
48-token rollouts (reference test_config: gen len 48, batch 16,
128 rollouts/phase, 4 ppo_epochs). One full PPO phase = collect 128 rollouts
(compiled sampler + reward + KL penalty vs frozen ref) + 32 optimizer steps
(8 minibatches x 4 ppo_epochs). Weights are randomly initialized (zero-
egress environment: no HF downloads) — identical compute to the pretrained
model.

BOTH workload definitions are measured every round (VERDICT r4 #1):

- **Headline (`value`): the faithful reconstruction of the reference as
  shipped.** In the actual reference code the PPO-path freezing block is
  COMMENTED OUT (`accelerate_base_model.py:55-69`) — with test_config.yml's
  `num_layers_unfrozen: 2` the policy still trains ALL 12 layers; the
  setting only sizes the hydra frozen KL-ref branch (`ppo_models.py:
  525-536`). Expressed here as `num_layers_unfrozen: 0` +
  `ref_branch_layers: 2` (full training, 2-layer hydra ref). This is the
  same definition rounds 1-3 measured (they paid a FULL-COPY ref — strictly
  more ref compute than the reference's own hydra branch).
- **Secondary (`value_frozen_top2`): the lightened workload round 4
  mistakenly reported as faithful** (freezing re-enabled: only the top 2
  blocks train, backward pruned below the branch point). Kept for series
  continuity with BENCH_r04 and as the work-avoidance capability number.

MFU accounting charges only performed FLOPs per definition (_phase_flops).

The reference publishes no numbers (BASELINE.md), so the falsifiable
claims here are the hardware-grounded ones: decode/train tokens/s,
achieved FLOP/s, and MFU against the chip's published bf16 peak (FLOP
accounting below). ``vs_baseline`` is kept for continuity against a
documented single-A100 *estimate* for torch trlX on this workload
(HF generate rollouts + DDP updates, ~12 samples/s) — an estimate, not a
measurement.

Prints one JSON line: {"metric", "value", "unit", "vs_baseline", + extras}.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

A100_BASELINE_SAMPLES_PER_SEC = 12.0

# BENCH payload schema: bump when a top-level key changes meaning, so
# round-over-round diffs (and the run-ledger compare) are
# machine-checkable against the layout they were written under.
BENCH_SCHEMA_VERSION = 1

# Published per-chip peaks (bf16 TFLOP/s, HBM GB/s) by device_kind —
# single source shared with the attribution layer
# (telemetry/attribution.py), which adds documented NOMINAL fallbacks
# for backends without a published spec.
from trlx_tpu.telemetry.attribution import (  # noqa: E402
    BF16_PEAK_TFLOPS,
    HBM_PEAK_GBPS,
)


def _collect_bytes(d, V, L, Q, R, B, kv_cache_bytes=1, weight_bytes=2):
    """Architecturally-required HBM bytes for one collect phase — the
    roofline denominator for ``collect_phase_hbm_util`` (VERDICT r3 #2).
    Decode is memory-bound, so MFU alone cannot distinguish "near the HBM
    bound" from "leaving 2x on the table"; this counts the traffic the
    phase MUST move:

    - weights once per decode step (the defining cost of autoregressive
      decode: trunk + tied lm head, compute-dtype bytes), once for
      prefill, once for the frozen-ref forward;
    - KV cache: read of all prior positions + one-position write per
      step, at the cache dtype (int8 here);
    - the per-step logits pipeline ([B, V] f32 written by the head, then
      read by eos-suppression/sampling/logsumexp — counted as 4 passes).

    Activations inside fused layers are NOT counted (they live in
    VMEM/registers when fusion works), so the number is a *lower bound* on
    true traffic and the util an *upper bound* on unavoidable-traffic
    efficiency.

    ``B`` must be the PER-CHIP batch: under dp replication every chip
    streams the full weights itself (weight terms don't divide over
    chips), while cache/logits traffic scales with the chip's batch
    shard."""
    w_step = (L * (12 * d * d + 13 * d) + V * d + 2 * d) * weight_bytes
    cache_read = sum(
        2 * L * B * (Q + t + 1) * d * kv_cache_bytes for t in range(R)
    )
    cache_write = R * 2 * L * B * d * kv_cache_bytes
    logits = R * 4 * B * V * 4
    decode = R * w_step + cache_read + cache_write + logits
    prefill = w_step + 2 * L * B * Q * d * kv_cache_bytes
    ref = w_step + 2 * B * R * V * 4
    return decode + prefill + ref


def _train_step_bytes(d, V, L, Q, R, B, unfrozen=0):
    """Architecturally-required HBM bytes for ONE optimizer step — the
    roofline denominator for ``train_phase_hbm_util`` (VERDICT r4 #2,
    mirrors bench_train_audit.py). Lower bound: fused per-layer
    activations uncounted.

    - weights: the fwd reads the full bf16 compute cast; the bwd is
      PRUNED below the branch point (matching `_phase_flops`), so it
      re-reads only the unfrozen blocks + the (tied) head transpose for
      dlogits; f32 grads written for the trainable slice;
    - optimizer: trainable slice only (frozen leaves carry no moments and
      take no update — the mask freezes wte/wpe + bottom blocks, so the
      trainable slice is the unfrozen blocks + ln_f, NOT a flat fraction
      of all params);
    - logits pipeline: the [B, R, V] f32 buffer crosses HBM ~5 times
      (head write, logsumexp read, bwd softmax rebuild+read, dlogits
      write+read into the head transpose);
    - residual stream saved for bwd (bf16 write+read per unfrozen layer).
    """
    blocks = L * (12 * d * d + 13 * d)
    head = V * d
    n_params = blocks + head + 2 * d
    frac = unfrozen / L if 0 < unfrozen < L else 1.0
    # all-trainable: every param (incl. wte/wpe). Frozen: unfrozen blocks
    # + ln_f only — the mask freezes the embeddings, and the tied head
    # weight IS the frozen wte (value head negligible)
    trainable = n_params if frac == 1.0 else blocks * frac + 2 * d
    weights = (
        2 * n_params            # fwd reads the full bf16 cast
        + 2 * (blocks * frac + head)  # pruned bwd re-reads
        + 4 * trainable         # f32 grads written
    )
    optimizer = 4 * trainable + 16 * trainable + 8 * trainable
    logits = 5 * B * R * V * 4
    acts = 2 * 2 * B * (Q + R) * d * (L * frac)
    return weights + optimizer + logits + acts


def _phase_flops(d, V, L, Q, R, B, ppo_epochs, unfrozen=0):
    """Total matmul FLOPs for one PPO phase (collect + train), exact —
    counting only FLOPs the programs actually perform.

    Trunk weights touched per token: qkv+proj (4 d^2) + mlp (8 d^2) per
    layer. Attention scores/values: 4*d*c FLOPs per token at context
    length c per layer (QK^T and AV, 2 FLOPs/MAC). The lm_head (d*V) is
    counted only where the code actually applies it: the last prefill
    position (`last_only` sampling), each decode step, and the R response
    positions in ref scoring / training (`response_forward` slices hidden
    to responses before the heads). Value head and layernorms negligible.

    With ``unfrozen=k > 0`` (the frozen-top2 SECONDARY workload — the
    reference as shipped trains all layers, its freezing block being
    commented out): the backward is pruned below the branch point
    (stop_gradient + dead-code elimination), so bwd = 2x the top-k trunk
    slice + one d_hidden matmul through the (frozen, tied) lm head.

    The ref term is one full-depth pass in BOTH definitions: a hydra ref
    is (L-k) shared-trunk layers (XLA prunes the capture pass's top-k —
    only branch_hidden is consumed; pinned by
    ``test_freezing.py::test_hydra_capture_flops_match_truncated_trunk``)
    plus k frozen-branch layers + head, and a full-copy ref is L layers +
    head — identical FLOPs.
    """
    trunk = L * 12 * d * d
    T = Q + R

    def trunk_fwd(tokens, ctx_sum, frac=1.0):
        return frac * (2 * trunk * tokens + 4 * L * d * ctx_sum)

    def fwd(tokens, ctx_sum, head_tokens):
        return trunk_fwd(tokens, ctx_sum) + 2 * d * V * head_tokens

    # collect: prefill over Q (logits at the last position only), R
    # single-token decode steps at growing context, and the frozen-ref
    # forward over T with logits at the R response positions
    prefill = fwd(Q, Q * (Q + 1) // 2, 1)
    decode = fwd(R, sum(Q + t + 1 for t in range(R)), R)
    ctx_T = T * (T + 1) // 2
    if 0 < unfrozen < L:
        frac = unfrozen / L
        # hydra ref executes exactly one full-depth pass: (L-k) shared
        # trunk layers (XLA prunes the capture pass's top-k — only
        # branch_hidden is consumed) + k frozen-branch layers + head
        ref = fwd(T, ctx_T, R)
        bwd = 2 * trunk_fwd(T, ctx_T, frac) + 2 * d * V * R  # pruned
    else:
        ref = fwd(T, ctx_T, R)
        bwd = 2 * fwd(T, ctx_T, R)
    collect = B * (prefill + decode + ref)
    train = ppo_epochs * B * (fwd(T, ctx_T, R) + bwd)
    return collect, train

def _reward_tier(budget_seconds=300.0, eps=0.01, patience=4, min_phases=8):
    """The BASELINE metric's other half: mean reward, measured to PLATEAU —
    PPO-steer the locally-pretrained two-topic stand-in checkpoint (the
    offline tier of the reference's gpt2-imdb + distilbert sentiment
    workload, `examples/ppo_sentiments.py:23-54`) until the full-eval mean
    reward stops improving (< ``eps`` gain over the best in ``patience``
    consecutive evals) or the wall-clock budget runs out. Reward is in
    [-1, 1] (response-token sentiment), starting near 0 on balanced
    prompts; the artifact records the whole per-eval curve, so it answers
    "how good does the policy get", not just "did it move" (VERDICT r3 #5).
    """
    import numpy as np

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "examples"))
    try:
        from trlx_tpu.data.configs import TRLConfig
        from trlx_tpu.utils.loading import (
            get_orchestrator, get_pipeline, get_trainer,
        )
        from pretrained_standin import (
            causal_rl_config, ensure_gpt2_checkpoint, make_prompts,
            sentiment_reward,
        )

        ckpt_dir = ensure_gpt2_checkpoint()
        config = TRLConfig.from_dict(causal_rl_config(ckpt_dir))
        trainer = get_trainer(config.train.trainer)(
            config, reward_fn=sentiment_reward
        )
        pipeline = get_pipeline(config.train.pipeline)(
            make_prompts(np.random.default_rng(1), 256, 8),
            config.train.seq_length,
        )
        orch = get_orchestrator(config.train.orchestrator)(
            trainer, pipeline, reward_fn=sentiment_reward,
            chunk_size=config.method.chunk_size,
        )
        # eval on the same prompt set as rounds 1-3 (api.train defaults
        # eval_prompts to the training prompts by reusing the pipeline
        # object — create_loader returns independent generators)
        trainer.add_eval_pipeline(pipeline)

        t0 = time.time()
        curve = [round(float(trainer.evaluate()["reward/mean"]), 4)]
        updates_per_phase = config.method.ppo_epochs * (
            config.method.num_rollouts // config.train.batch_size
        )
        phases = 0
        plateaued = False
        while time.time() - t0 < budget_seconds:
            trainer.buffer.clear_history()
            orch.make_experience(config.method.num_rollouts, phases)
            trainer.train_on_buffer(seed=config.train.seed + phases)
            phases += 1
            curve.append(round(float(trainer.evaluate()["reward/mean"]), 4))
            # plateau only counts after the slow-start window: the curve
            # sits near 0 for the first ~half-dozen phases before moving
            if (
                phases >= min_phases
                and max(curve[-patience:]) < max(curve[:-patience]) + eps
            ):
                plateaued = True
                break
        return {
            "mean_reward_pre": curve[0],
            "mean_reward_post": curve[-1],
            "reward_plateau": max(curve),
            # updates to the PEAK eval (curve[0] is the pre-train eval),
            # not to loop exit — the patience tail is excluded
            "reward_plateau_steps": curve.index(max(curve)) * updates_per_phase,
            "reward_plateaued": plateaued,
            "reward_curve": curve,
            "reward_tier_seconds": round(time.time() - t0, 1),
        }
    except Exception as e:  # the throughput number must still print
        return {"mean_reward_error": f"{type(e).__name__}: {e}"}


def _workload_config(num_layers_unfrozen, ref_branch_layers):
    """The BASELINE workload at one of the two freezing definitions.

    Faithful (headline): ``(0, 2)`` — the reference as shipped trains ALL
    layers (freezing commented out, `accelerate_base_model.py:55-69`) with
    the 2-layer hydra KL-ref branch that `test_config.yml:5` actually
    sizes. Frozen-top2 (secondary): ``(2, None)`` — freezing re-enabled.
    """
    from trlx_tpu.data.configs import TRLConfig

    # rollout engine selection (docs/inference.md): default stays the
    # fixed-batch sampler so the r01-r05 series keeps comparing; set
    # TRLX_BENCH_ROLLOUT_ENGINE=continuous to measure the slot-admission
    # engine (the payload then carries collect/admit_ms + slot_util next
    # to the phase tree)
    rollout_engine = os.environ.get("TRLX_BENCH_ROLLOUT_ENGINE", "fixed")
    # asynchronous actor–learner mode (docs/async_pipeline.md): set
    # TRLX_BENCH_ASYNC_RL=1 to run the phases on the async schedule
    # (forces the continuous engine; TRLX_BENCH_ASYNC_STALENESS tunes
    # the window, default 1). The default fixed-path r01–r05 series
    # stays comparable — async is opt-in per round, and the payload
    # then carries async/staleness_p50, async/learner_idle_ms and the
    # actor/learner occupancy next to the span tree.
    async_rl_on = os.environ.get("TRLX_BENCH_ASYNC_RL") == "1"
    async_rl = (
        {
            "enabled": True,
            "staleness_window": int(
                os.environ.get("TRLX_BENCH_ASYNC_STALENESS", "1")
            ),
        }
        if async_rl_on
        else {}
    )
    if async_rl_on:
        rollout_engine = "continuous"

    return TRLConfig.from_dict(
        {
            "model": {
                "model_type": "gpt2",
                "num_layers_unfrozen": num_layers_unfrozen,
                "ref_branch_layers": ref_branch_layers,
                "model_arch": {
                    "vocab_size": 50257,
                    "n_positions": 1024,
                    "n_embd": 768,
                    "n_layer": 12,
                    "n_head": 12,
                    # "auto" resolves to int8 at this cache shape (cap
                    # 112 <= INT8_KV_MAX_CAPACITY): measured 1.10x on the
                    # sampler (interleaved A/B, ab_int8_kv.py) — decode is
                    # HBM-bound and the cache is its dominant traffic.
                    # bf16 beyond the measured long-context crossover.
                    "kv_cache_dtype": "auto",
                },
            },
            "train": {
                "seq_length": 64,
                "batch_size": 16,
                "epochs": 3,
                "total_steps": 10000,
                "eval_interval": 100000,
                "checkpoint_interval": 1000000,
                "lr_init": 1.412e-4,
                "lr_target": 1.412e-4,
                "mesh": {"dp": -1, "fsdp": 1, "tp": 1},
                "dtype": "bfloat16",
                # run-health monitoring on (docs/observability.md): the
                # fused health scalars ride the phase's existing stats
                # transfer and the detector/event counts ship in the
                # BENCH payload — a bench round that tripped kl-spike or
                # entropy-collapse is not a clean perf sample.
                # SERIES NOTE (r06+): enabling health adds real device
                # work to the timed train step (full-vocab softmax
                # entropy at ent_coef=0, reward quantiles) — a one-time,
                # instrumentation-caused discontinuity vs the r01-r05
                # series; attribute any small train-phase delta at r06
                # here first before hunting regressions (the CPU perf
                # gate's harness keeps health off, so engine 10's
                # lockfile is unaffected)
                "health": {"enabled": True},
                "rollout": {"engine": rollout_engine},
                "async_rl": async_rl,
            },
            "method": {
                "name": "PPOConfig",
                "num_rollouts": 128,
                "chunk_size": 128,
                "ppo_epochs": 4,
                "init_kl_coef": 0.2,
                "target": 6,
                "horizon": 10000,
                "cliprange_reward": 10,
                "scale_reward": "running",
                "gen_kwargs": {
                    "max_new_tokens": 48,
                    # fixed-length rollouts, as the reference workload
                    # (ppo_config.yml: min_length == max_length)
                    "min_new_tokens": 48,
                    "top_k": 0,
                    "do_sample": True,
                    "eos_token_id": 50256,
                    "pad_token_id": 50256,
                },
            },
        }
    )

def measure_fetch_overhead(trials=3):
    """Flat tunnel round-trip cost of one forcing fetch, measured on a
    FRESH ready array per trial — jax.Array caches the host value after
    the first device_get, so re-fetching the same array times ~0 and
    would silently no-op the correction."""
    import jax
    import jax.numpy as jnp

    best = float("inf")
    for i in range(trials):
        arr = jax.block_until_ready(jnp.full((), float(i)))
        t0 = time.time()
        float(jax.device_get(arr))
        best = min(best, time.time() - t0)
    return best


def measure_throughput(config, n_phases=5):
    """Run the PPO phase loop for one workload definition and return the
    hardware-grounded metrics (samples/s/chip, tok/s, MFU, HBM util)."""
    import jax
    import numpy as np

    from trlx_tpu.utils.loading import get_orchestrator, get_pipeline, get_trainer

    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(100, 40000, size=rng.integers(4, 33)))
               for _ in range(512)]

    def reward_fn(samples, queries, response_gt=None):
        # cheap host reward: length-normalized char diversity
        return [len(set(s)) / max(len(s), 1) for s in samples]

    trainer = get_trainer(config.train.trainer)(config, reward_fn=reward_fn)
    pipeline = get_pipeline(config.train.pipeline)(
        prompts, config.train.seq_length
    )
    orch = get_orchestrator(config.train.orchestrator)(
        trainer, pipeline, reward_fn=reward_fn, chunk_size=config.method.chunk_size
    )

    # compile accounting (docs/static_analysis.md, engine 8): the same
    # monitor the --compile-audit gate uses counts every actual XLA
    # compile over the bench's phase loop, so a retrace burning wall
    # clock shows up NEXT TO the throughput number it depressed
    from trlx_tpu.analysis.compile_audit import CompileMonitor

    monitor = CompileMonitor()

    # span accounting (docs/observability.md): the phase loop is
    # instrumented by the telemetry tracer — the measured window's span
    # tree ships in the BENCH payload under stable keys so the perf
    # trajectory is machine-diffable across rounds (engine 10 gates the
    # same spans on the CPU tier)
    from trlx_tpu import telemetry

    tracer = telemetry.configure(enabled=True)

    times = {"collect": 0.0, "train": 0.0}
    overlap_saved = {"ms": 0.0, "phases": 0}
    # cost of one forcing fetch = the flat tunnel round trip; subtracted
    # from each train window below so the fetch doesn't inflate the series
    fetch_overhead = measure_fetch_overhead()
    phase_seed = [0]

    def one_phase(record=False):
        trainer.buffer.clear_history()
        phase_seed[0] += 1
        # streamed phase (the production default, docs/async_pipeline.md):
        # epoch-1 updates dispatch during collection; epochs 2..E run as
        # the fused residual scan in finish_streamed_phase. Falls back to
        # the legacy fused pass when overlap is disabled in the config.
        streamed = config.train.phase_overlap
        t0 = time.time()
        if streamed:
            trainer.begin_streamed_phase(seed=phase_seed[0])
        orch.make_experience(config.method.num_rollouts, 0)
        # make_experience ends on host-side reward work; the buffer is
        # device-resident, so the collect/train split is the dispatch
        # boundary here (the train window's block covers any tail — note
        # that with overlap on, epoch-1 device work already ran inside
        # the collect window: that is the effect being measured)
        t1 = time.time()
        if streamed:
            _, phase_rows, _ = trainer.finish_streamed_phase()
            phase_stats = phase_rows  # host rows already fetched
        else:
            # one fused dispatch for all minibatch x ppo_epoch updates
            _, phase_stats, _ = trainer.train_on_buffer()
        # force with a REAL device->host transfer of a program output:
        # block_until_ready alone intermittently no-ops on the tunneled
        # backend (measured: a 550 ms phase "finishing" in 2.8 ms), which
        # would shift train time into the next phase's collect window
        jax.block_until_ready(trainer.state.params)
        float(np.asarray(jax.device_get(next(iter(
            jax.tree_util.tree_leaves(phase_stats)
        )))).ravel()[0])
        t2 = time.time()
        if record:
            times["collect"] += t1 - t0
            times["train"] += (t2 - t1) - fetch_overhead
            if streamed:
                overlap_saved["ms"] += trainer._last_overlap_stats.get(
                    "exp/overlap_saved_ms", 0.0
                )
                overlap_saved["phases"] += 1

    # __exit__ MUST run even when a phase raises: the monitor holds jax's
    # pxla/dispatch loggers at DEBUG with a handler attached, and a leaked
    # handler swallows compile logs process-wide (counts stay readable
    # after exit)
    monitor.__enter__()
    try:
        one_phase()  # warmup: compile sampler + fused train phase
        one_phase()  # 2nd warmup: absorbs any donated-buffer relayout retrace
        monitor.mark_steady()  # any compile past here retraced mid-measurement
        tracer.clear()  # span stats cover the measured phases only

        start = time.time()
        for _ in range(n_phases):
            one_phase(record=True)
        # the forcing fetches are measurement apparatus, not workload
        elapsed = time.time() - start - n_phases * fetch_overhead
    finally:
        monitor.__exit__(None, None, None)

    n_chips = len(jax.devices())
    samples_per_sec = n_phases * config.method.num_rollouts / elapsed
    per_chip = samples_per_sec / n_chips

    # hardware-grounded numbers: tokens/s per phase, FLOP/s, MFU
    arch = config.model.model_arch
    B, Q = config.method.num_rollouts, config.train.seq_length
    R = config.method.gen_kwargs["max_new_tokens"]
    collect_flops, train_flops = _phase_flops(
        d=arch["n_embd"], V=arch["vocab_size"], L=arch["n_layer"],
        Q=Q, R=R, B=B, ppo_epochs=config.method.ppo_epochs,
        unfrozen=config.model.num_layers_unfrozen,
    )
    kind = jax.devices()[0].device_kind
    peak = BF16_PEAK_TFLOPS.get(kind)
    achieved_tflops = (
        n_phases * (collect_flops + train_flops) / elapsed / n_chips / 1e12
    )
    out = {
        "value": round(per_chip, 3),
        # generated tokens over the whole collect window (incl. prefill,
        # frozen-ref forward, host reward) — rollout throughput, not a
        # bare decode-step rate
        "rollout_tok_per_sec_per_chip": round(
            n_phases * B * R / times["collect"] / n_chips, 1
        ),
        "train_tok_per_sec_per_chip": round(
            n_phases * config.method.ppo_epochs * B * (Q + R)
            / times["train"] / n_chips,
            1,
        ),
        "achieved_tflops_per_chip": round(achieved_tflops, 2),
        "device_kind": kind,
        "collect_ms_per_phase": round(times["collect"] / n_phases * 1e3, 1),
        "train_ms_per_phase": round(times["train"] / n_phases * 1e3, 1),
    }
    if overlap_saved["phases"]:
        # per-phase estimate of epoch-1 device time hidden under the
        # collect window by the streamed schedule (docs/async_pipeline.md;
        # ground truth for the wall-clock delta is ab_phase_overlap.py)
        out["exp/overlap_saved_ms"] = round(
            overlap_saved["ms"] / overlap_saved["phases"], 1
        )
    # async actor–learner attribution (TRLX_BENCH_ASYNC_RL=1,
    # docs/async_pipeline.md): staleness distribution, learner idle,
    # and actor/learner occupancy of the last measured phase ride the
    # payload next to the span tree (ground truth for the wall-clock
    # delta is ab_async_rl.py, which self-records)
    for key in (
        "async/staleness_p50", "async/staleness_max",
        "async/consumed_lag_p50", "async/consumed_lag_max",
        "async/learner_idle_ms", "async/guard_hold_ms",
        "async/actor_occupancy", "async/learner_occupancy",
        "async/weight_pushes",
    ):
        if key in trainer._last_overlap_stats:
            out[key] = round(float(trainer._last_overlap_stats[key]), 4)
    if peak:
        out["mfu"] = round(achieved_tflops / peak, 4)
        out["bf16_peak_tflops"] = peak
        out["train_phase_mfu"] = round(
            n_phases * train_flops / times["train"] / n_chips / 1e12 / peak, 4
        )
        # the weakest phase gets its own falsifiable number (VERDICT r2):
        # collect = compiled sampler + frozen-ref forward + host reward
        out["collect_phase_mfu"] = round(
            n_phases * collect_flops / times["collect"] / n_chips / 1e12 / peak,
            4,
        )
    hbm_peak = HBM_PEAK_GBPS.get(kind)
    if hbm_peak:
        # per-chip traffic: weights replicate over dp (each chip streams
        # them in full), cache/logits follow the chip's batch shard
        from trlx_tpu.models.gpt2 import resolve_kv_cache_dtype

        kv_dtype = resolve_kv_cache_dtype(
            arch.get("kv_cache_dtype", "bfloat16"), Q + R
        )
        per_chip_bytes = _collect_bytes(
            d=arch["n_embd"], V=arch["vocab_size"], L=arch["n_layer"],
            Q=Q, R=R, B=B // n_chips,
            kv_cache_bytes=1 if kv_dtype == "int8" else 2,
        )
        gbps = n_phases * per_chip_bytes / times["collect"] / 1e9
        out["collect_phase_hbm_gbps"] = round(gbps, 1)
        out["collect_phase_hbm_util"] = round(gbps / hbm_peak, 4)
        # train-phase roofline next to its MFU (VERDICT r4 #2): required
        # bytes per step x steps over measured train time
        steps = config.method.ppo_epochs * (B // config.train.batch_size)
        step_bytes = _train_step_bytes(
            d=arch["n_embd"], V=arch["vocab_size"], L=arch["n_layer"],
            Q=Q, R=R, B=config.train.batch_size // n_chips,
            unfrozen=config.model.num_layers_unfrozen,
        )
        tgbps = n_phases * steps * step_bytes / times["train"] / 1e9
        out["train_phase_hbm_gbps"] = round(tgbps, 1)
        out["train_phase_hbm_util"] = round(tgbps / hbm_peak, 4)
    # per-phase span tree over the measured window (stable keys: the
    # engine-10 gated spans as flat *_ms p50s + the full stats table) —
    # the round-over-round perf diff reads these instead of eyeballing
    # collect_ms/train_ms
    span_stats = tracer.stats()
    for key, flat in (
        ("phase/collect", "phase/collect_ms"),
        ("phase/train", "phase/train_ms"),
        ("train/drain", "phase/drain_ms"),
        # continuous-engine decode-loop spans (docs/inference.md):
        # admission bookkeeping, prefill dispatch, harvest/recycle —
        # present only when the engine ran this round
        ("collect/admit", "collect/admit_ms"),
        ("collect/prefill", "collect/prefill_ms"),
        ("collect/slot_recycle", "collect/slot_recycle_ms"),
    ):
        if key in span_stats:
            out[flat] = round(span_stats[key]["p50_ms"], 1)
    # slot-occupancy stats ride the payload next to the span tree when
    # the continuous engine collected this round
    if (
        getattr(trainer, "rollout_engine", "fixed") == "continuous"
        and getattr(trainer, "_rollout_engine_obj", None) is not None
    ):
        engine_stats = trainer._rollout_engine_obj.stats.to_dict()
        # one canonical key for occupancy; the remaining engine/*
        # counters keep their namespaced names
        out["slot_util"] = engine_stats.pop("engine/slot_util")
        out.update(engine_stats)
    out["spans"] = {
        name: {
            "count": int(s["count"]),
            "p50_ms": round(s["p50_ms"], 2),
            "p95_ms": round(s["p95_ms"], 2),
            "total_ms": round(s["total_ms"], 1),
        }
        for name, s in span_stats.items()
    }
    # ring evictions skew the p50s above with no other signal — surface
    # the count in the payload and warn once on stderr when nonzero
    out["spans_dropped"] = telemetry.warn_on_span_drops(tracer)
    # utilization attribution (telemetry/attribution.py,
    # docs/observability.md): engine-7 statics ÷ the measured span walls
    # above — measured MFU + HBM-BW util per traced program, the async
    # bubble breakdown, and phase goodput. The table prints to stderr
    # (stdout stays one JSON line); the payload carries the same rows.
    out.update(
        _attribution_payload(trainer, config, span_stats, n_phases, n_chips)
    )
    # run-health summary (docs/observability.md): detector trip counts
    # over the measured window (a tripped kl-spike/entropy-collapse
    # means the throughput sample rode a diverging run) + the last
    # observed training-dynamics scalars. NOTE: distinct name — the
    # health block used to rebind `monitor` (the CompileMonitor), so
    # every health-enabled bench run crashed at the compile-counts
    # epilogue below with HealthMonitor.counts()
    health_mon = getattr(trainer, "health_monitor", None)
    if health_mon is not None:
        out["health_events"] = dict(sorted(health_mon.event_counts.items()))
        out["health"] = health_mon.health_summary()
    static_res = _static_resources(trainer)
    out.update(static_res)
    out.update(_compiled_resources(trainer, static_res))
    out.update(
        _measured_memory(static_res.get("static_train_step_peak_hbm_gb"))
    )
    # per-callable compile counts + trace/compile wall time over the
    # whole run (warmups included); steady_compiles > 0 means a program
    # RETRACED inside the measured window — the throughput above paid
    # for XLA time and the run deserves a --compile-audit triage. One-off
    # warmup compiles of eager primitives are folded into a single total
    # so the phase programs (and anything that compiled twice) stand out.
    counts = monitor.counts()
    steady = monitor.counts(steady_only=True)
    phase_programs = {
        "sampler", "train_step", "train_phase", "behavior_snapshot",
    }
    out["compile_counts"] = {
        name: n
        for name, n in sorted(counts.items())
        if name in phase_programs or n > 1 or steady.get(name)
    }
    out["eager_op_compiles"] = sum(
        n for name, n in counts.items()
        if name not in out["compile_counts"]
    )
    if steady:
        out["steady_compiles"] = dict(sorted(steady.items()))
    out["trace_seconds"] = round(monitor.trace_seconds, 1)
    out["compile_seconds"] = round(monitor.compile_seconds, 1)
    # metrics snapshot for THIS workload's ledger manifest — the
    # registry is process-global, so without capturing here the frozen
    # secondary run would overwrite the gauges the faithful manifest
    # reports; main() pops this before printing the JSON line
    out["_metrics_snapshot"] = telemetry.get_metrics().snapshot()
    return out


def _attribution_payload(trainer, config, span_stats, n_phases, n_chips):
    """Measured-MFU ledger for the bench window (docs/observability.md,
    "Utilization attribution"): engine-7 statics traced at the REAL
    workload shape joined with the measured span walls. Prints the
    "where did the time go" table + async bubble breakdown to stderr;
    returns the machine-readable payload keys. Guarded — the headline
    numbers must still print if any trace drifts."""
    try:
        import jax

        from trlx_tpu.telemetry import attribution

        method = config.method
        n_mb = max(method.num_rollouts // config.train.batch_size, 1)
        resources = attribution.trainer_program_resources(
            trainer,
            kind="ppo",
            chunk_size=method.chunk_size,
            residual_len=n_mb * max(method.ppo_epochs - 1, 0),
        )
        engine = (
            "continuous"
            if getattr(trainer, "rollout_engine", "fixed") == "continuous"
            else "fixed"
        )
        counts = {}
        if getattr(trainer, "_rollout_engine_obj", None) is not None:
            # EngineStats resets every start_phase, so the counters
            # cover the LAST measured phase only, while the span walls
            # accumulate over all n_phases — scale to the whole window
            # (identical workload per phase) or the count_key rows
            # would understate utilization by n_phases x
            counts.update(
                {
                    k: v * n_phases
                    for k, v in trainer._rollout_engine_obj.stats.to_dict().items()
                    if isinstance(v, (int, float))
                    and k != "engine/slot_util"  # a ratio, not a counter
                }
            )
        rows = attribution.attribute(
            resources,
            span_stats,
            device_kind=jax.devices()[0].device_kind,
            n_devices=n_chips,
            work=attribution.default_work(engine),
            counts=counts,
        )
        bubbles = attribution.bubble_breakdown(
            span_stats,
            getattr(trainer, "_last_overlap_stats", None),
            phases=n_phases,
        )
        goodput = attribution.phase_goodput(
            span_stats, method.num_rollouts, phases=n_phases
        )
        print(
            attribution.format_attribution(rows, bubbles, goodput),
            file=sys.stderr,
        )
        out = {
            "attribution": [r.to_dict() for r in rows],
            "bubbles": {
                k: round(v, 4) for k, v in bubbles.items()
            },
        }
        if "goodput_samples_per_sec" in goodput:
            out["goodput_samples_per_sec"] = round(
                goodput["goodput_samples_per_sec"], 3
            )
        return out
    except Exception as e:  # the measured numbers must still print
        return {"attribution_error": f"{type(e).__name__}: {e}"}


def _static_resources(trainer):
    """Static resource-auditor numbers for the jitted train step at the
    REAL workload shape (docs/static_analysis.md, engine 6) — tracing
    only, no compilation. Printed next to the measured stats so every
    bench run surfaces the same contracts CI gates: peak live HBM per
    device (donation- and sharding-aware), modeled collective bytes, and
    counted step FLOPs (an exact-arithmetic cross-check of
    ``_phase_flops``' closed form)."""
    try:
        from trlx_tpu.analysis.resource_audit import trainer_step_resources

        res = trainer_step_resources(trainer)
        return {
            "static_train_step_peak_hbm_gb": round(
                res.peak_hbm_bytes / 2**30, 3
            ),
            "static_train_step_collective_mb": round(
                res.collective_bytes / 2**20, 3
            ),
            "static_train_step_gflops": round(res.flops / 1e9, 1),
        }
    except Exception as e:  # the measured numbers must still print
        return {"static_resource_error": f"{type(e).__name__}: {e}"}


def _compiled_resources(trainer, static_res):
    """Compiled ground truth next to the engine-6 statics
    (docs/static_analysis.md, engine 13): the train step's actual
    post-SPMD HLO collective payload and buffer-assignment peak from
    the SAME jit instance the bench drives (the step is already
    compiled by the measured window, so this re-lowers from cache).
    The ``static_vs_compiled`` ratios are the live twin of the
    hlo-memory-drift / collective-profile gates CI runs — a bench
    round where compiled/static drifts while the lockfile is green
    means the bench shape diverged from the audit shape, not XLA."""
    try:
        from trlx_tpu.analysis.hlo_audit import compiled_step_stats

        kind = (
            "ilql"
            if trainer.__class__.__name__.startswith("ILQL")
            else "ppo"
        )
        stats = compiled_step_stats(trainer, kind)
        out = {
            k: round(v, 3) for k, v in stats.items()
        }
        ratios = {}
        static_mb = static_res.get("static_train_step_collective_mb")
        if static_mb and "compiled_train_step_collective_mb" in stats:
            ratios["collective_mb_compiled_over_static"] = round(
                stats["compiled_train_step_collective_mb"] / static_mb, 3
            )
        static_gb = static_res.get("static_train_step_peak_hbm_gb")
        if static_gb and "compiled_train_step_peak_hbm_gb" in stats:
            ratios["peak_hbm_compiled_over_static"] = round(
                stats["compiled_train_step_peak_hbm_gb"] / static_gb, 3
            )
        if ratios:
            out["static_vs_compiled"] = ratios
        return out
    except Exception as e:  # the measured numbers must still print
        return {"compiled_resource_error": f"{type(e).__name__}: {e}"}


def _measured_memory(static_peak_gb):
    """Allocator-measured HBM next to the static engine-7 prediction
    (telemetry/device_metrics.py). The measured value is the PROCESS
    peak (sampler + snapshot + stream store + train step together), so
    the ratio against the static train-step contract is a
    phase-footprint signal — a round-over-round rise means the run's
    memory grew somewhere the step lockfile does not gate. Reuses the
    static number `_static_resources` already computed (the engine-7
    trace costs seconds at the bench shape). Empty on backends without
    memory_stats (CPU)."""
    try:
        from trlx_tpu.telemetry.device_metrics import static_vs_measured

        static_bytes = (
            int(static_peak_gb * 2**30) if static_peak_gb else None
        )
        res = static_vs_measured(static_peak_bytes=static_bytes)
        out = {}
        if "measured_peak_hbm_bytes" in res:
            out["measured_peak_hbm_gb"] = round(
                res["measured_peak_hbm_bytes"] / 2**30, 3
            )
        if "measured_process_peak_over_static_step" in res:
            out["measured_process_peak_over_static_step"] = res[
                "measured_process_peak_over_static_step"
            ]
        return out
    except Exception as e:  # the measured numbers must still print
        return {"measured_memory_error": f"{type(e).__name__}: {e}"}


def main():
    os.environ.setdefault("WANDB_DISABLED", "1")

    # HEADLINE: faithful reconstruction of the reference as shipped — all
    # 12 layers train (the reference's PPO freezing is commented out),
    # 2-layer hydra KL-ref branch (what test_config.yml:5 actually sizes).
    # Same definition as the r1-r3 series (those paid a full-copy ref).
    faithful = measure_throughput(_workload_config(0, 2))
    # SECONDARY: the frozen-top2 workload r4 headline'd (freezing
    # re-enabled as work-avoidance; lighter train phase).
    frozen = measure_throughput(_workload_config(2, None))

    extras = dict(faithful)
    # the faithful (headline) workload's registry snapshot, for the
    # ledger manifest — never part of the printed JSON line
    metrics_snapshot = extras.pop("_metrics_snapshot", None)
    frozen.pop("_metrics_snapshot", None)
    per_chip = extras.pop("value")
    extras["value_frozen_top2"] = frozen["value"]
    extras["vs_baseline_frozen_top2"] = round(
        frozen["value"] / A100_BASELINE_SAMPLES_PER_SEC, 3
    )
    for k in ("train_tok_per_sec_per_chip", "train_phase_mfu",
              "train_ms_per_phase", "collect_ms_per_phase"):
        if k in frozen:
            extras[f"{k}_frozen_top2"] = frozen[k]

    extras.update(_reward_tier())
    ratio = per_chip / A100_BASELINE_SAMPLES_PER_SEC
    # machine-readable north-star (VERDICT r4 #7)
    extras["north_star_throughput_ratio"] = round(ratio, 3)
    extras["north_star_throughput_met"] = ratio >= 4.0
    extras["north_star_reward_status"] = "env-blocked-standin"
    if "reward_plateau" in extras:
        extras["standin_reward_plateau"] = extras["reward_plateau"]
        verb = (
            "plateaus at" if extras.get("reward_plateaued")
            else "reaches (budget-capped, still rising)"
        )
        extras["north_star"] = (
            f"throughput {per_chip:.0f} samples/s/chip (faithful full-train "
            f"workload) = {ratio:.1f}x the documented single-A100 torch-trlX "
            f"estimate (>=4x required); reward >=1.2 on gpt2-imdb+distilbert "
            f"is env-blocked (zero egress) — stand-in sentiment task {verb} "
            f"{extras['reward_plateau']} (range [-1,1]) after "
            f"{extras['reward_plateau_steps']} updates"
        )

    record = {
        "schema_version": BENCH_SCHEMA_VERSION,
        "metric": "ppo_samples_per_sec_per_chip_gpt2s",
        "value": per_chip,
        "unit": "samples/s/chip",
        "vs_baseline": round(per_chip / A100_BASELINE_SAMPLES_PER_SEC, 3),
        **extras,
    }
    print(json.dumps(record))

    # run ledger (telemetry/run_ledger.py): every bench round appends a
    # manifest — config fingerprint, platform, git sha, the attribution
    # table, and the full payload — so `python -m trlx_tpu.telemetry
    # --compare` diffs rounds mechanically. Best-effort: the JSON line
    # above is the contract output.
    try:
        from trlx_tpu.telemetry.run_ledger import (
            append_manifest,
            build_manifest,
            numeric_payload,
        )

        path = append_manifest(
            build_manifest(
                "bench",
                payload=numeric_payload(record),
                attribution=record.get("attribution") or [],
                span_stats=record.get("spans") or {},
                metrics=metrics_snapshot,
            )
        )
        print(f"bench: run manifest appended to {path}", file=sys.stderr)
    except Exception as e:
        print(f"bench: ledger append failed ({type(e).__name__}: {e})",
              file=sys.stderr)


if __name__ == "__main__":
    main()
