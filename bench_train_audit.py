"""Train-phase audit (VERDICT r4 #2): where do the non-MFU milliseconds go?

The faithful workload's train phase is now ~75% of phase wall-clock at
~31% MFU (BENCH r5: 579 ms/phase = 18.1 ms/step at B=16, T=112, 32
steps/phase). This audit decomposes one update step into separately-timed
components at the exact minibatch shape, then puts a HBM roofline next to
the MFU so "31% MFU" can be read correctly (compute-bound vs traffic-bound
vs neither):

- ``fwd``: policy forward -> response logprobs/values (incl. the [B,R,V]
  f32 logits materialization — the prime traffic suspect);
- ``fwd_bwd``: value_and_grad of the full PPO loss (adds the backward);
- ``gae_whiten``: advantages/returns + whitening (host-free, tiny?);
- ``optimizer``: AdamW update on precomputed grads (f32 m+v read+write is
  ~28 B/param — the other traffic suspect);
- ``train_step``: the real fused step; ``train_phase_per_step``: the real
  32-step scanned phase divided by 32 (captures scan-level fusion/layout
  wins and any dispatch overhead the components hide).

Methodology per the measurement traps on this tunneled chip: every
component loops ITERS times inside ONE jit via lax.scan with a real data
dependency (no per-iteration dispatch, no constant folding), one
block_until_ready, best of 3 — see bench_longctx.py.

Prints one JSON object with component ms, the component sum vs the real
step (unaccounted gap), the train-step HBM roofline, and the phase MFU.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

ITERS = 20


def timed(fn, *args):
    """Best-of-3 wall time of a jitted fn's device work (one dispatch)."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.time()
        jax.block_until_ready(fn(*args))
        best = min(best, time.time() - t0)
    return best


def main():
    os.environ.setdefault("WANDB_DISABLED", "1")
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from bench import (
        BF16_PEAK_TFLOPS, HBM_PEAK_GBPS, _phase_flops, _workload_config,
    )
    from trlx_tpu.data.ppo_types import PPORolloutBatch
    from trlx_tpu.ops.ppo_math import get_advantages_and_returns
    from trlx_tpu.utils.loading import get_trainer

    # default: the faithful (headline) workload; `frozen_top2` audits the
    # r4 secondary definition (freezing on, backward pruned) so the GAE-
    # hoist A/B exists on BOTH definitions (VERDICT r4 #2 asks for r4's)
    workload = sys.argv[1] if len(sys.argv) > 1 else "faithful"
    if workload not in ("faithful", "frozen_top2"):
        raise ValueError(
            f"unknown workload {workload!r}: expected 'faithful' or "
            f"'frozen_top2' (a typo here would mislabel the artifact)"
        )
    config = (
        _workload_config(2, None) if workload == "frozen_top2"
        else _workload_config(0, 2)
    )
    trainer = get_trainer(config.train.trainer)(
        config, reward_fn=lambda **kw: [0.0]
    )
    method = config.method
    B = config.train.batch_size
    Q = config.train.seq_length
    R = method.gen_kwargs["max_new_tokens"]
    arch = config.model.model_arch
    V, L, d = arch["vocab_size"], arch["n_layer"], arch["n_embd"]

    rng = np.random.default_rng(0)
    mb = PPORolloutBatch(
        query_tokens=jnp.asarray(rng.integers(100, 40000, (B, Q)), jnp.int32),
        query_mask=jnp.ones((B, Q), jnp.int32),
        response_tokens=jnp.asarray(
            rng.integers(100, 40000, (B, R)), jnp.int32
        ),
        response_mask=jnp.ones((B, R), jnp.int32),
        logprobs=jnp.asarray(rng.normal(size=(B, R)) - 8, jnp.float32),
        values=jnp.asarray(rng.normal(size=(B, R)) * 0.1, jnp.float32),
        rewards=jnp.asarray(rng.normal(size=(B, R)) * 0.1, jnp.float32),
    )
    state = trainer.state
    params = state.params

    def scan_loop(body, init_carry):
        """ITERS dependent iterations inside one jit (execution-cache and
        dispatch-latency safe on the tunneled chip)."""

        def wrapped(carry, _):
            return body(carry), None

        def run(c):
            c, _ = jax.lax.scan(wrapped, c, None, length=ITERS)
            return c

        return jax.jit(run), init_carry

    results = {}

    # --- fwd: forward -> logprobs/values (perturb params to carry a dep)
    def fwd_body(p):
        logprobs, values, _, _ = trainer._forward_logprobs_values(p, mb)
        eps = (jnp.mean(logprobs) + jnp.mean(values)) * 1e-30
        return jax.tree_util.tree_map(lambda x: x + eps.astype(x.dtype), p)

    fn, c = scan_loop(fwd_body, params)
    results["fwd_ms"] = timed(fn, c) / ITERS * 1e3
    print("fwd done", file=sys.stderr)

    # --- fwd+bwd: value_and_grad of the full PPO loss
    def loss_fn(p):
        logprobs, values, entropy, _ = trainer._forward_logprobs_values(p, mb)
        advantages, returns = trainer._advantages_and_returns(mb)
        from trlx_tpu.ops.ppo_math import ppo_loss

        loss, _ = ppo_loss(
            logprobs, values, mb.logprobs, mb.values, advantages, returns,
            mb.response_mask, method.cliprange, method.cliprange_value,
            method.vf_coef,
        )
        return loss

    def fwd_bwd_body(p):
        loss, grads = jax.value_and_grad(loss_fn)(p)
        eps = loss * 1e-30
        return jax.tree_util.tree_map(
            lambda x, g: x + (eps + 0 * jnp.mean(g)).astype(x.dtype), p, grads
        )

    fn, c = scan_loop(fwd_bwd_body, params)
    results["fwd_bwd_ms"] = timed(fn, c) / ITERS * 1e3
    print("fwd_bwd done", file=sys.stderr)

    # --- GAE + whitening alone (part of every loss eval)
    def gae_body(vals):
        adv, ret = get_advantages_and_returns(
            vals, mb.rewards, mb.response_mask, method.gamma, method.lam
        )
        return vals + jnp.mean(adv + ret) * 1e-30

    fn, c = scan_loop(gae_body, mb.values)
    results["gae_whiten_ms"] = timed(fn, c) / ITERS * 1e3
    print("gae done", file=sys.stderr)

    # --- optimizer: AdamW update on fixed grads. Grads are an ARGUMENT,
    # not a closure: closed-over arrays serialize into the program body
    # and the tunnel's compile endpoint rejects the 500 MB request
    # (HTTP 413)
    grads = jax.jit(jax.grad(loss_fn))(params)
    jax.block_until_ready(grads)

    def opt_run(carry, g):
        def body(c, _):
            p, opt_state = c
            updates, new_opt = trainer.tx.update(g, opt_state, p)
            return (optax.apply_updates(p, updates), new_opt), None

        c, _ = jax.lax.scan(body, carry, None, length=ITERS)
        return c

    fn = jax.jit(opt_run)
    results["optimizer_ms"] = (
        timed(fn, (params, state.opt_state), grads) / ITERS * 1e3
    )
    print("optimizer done", file=sys.stderr)

    # --- the real fused phase program at its real shape:
    # 32 pre-stacked minibatches = one phase dispatch. Methodology (the
    # tunnel's traps — an earlier run "measured" 2.8 ms for a 550 ms
    # phase): FRESH token inputs per call, built OUTSIDE the timed
    # window, and a forcing SCALAR FETCH of the program's stats output
    # (block_until_ready alone is not a reliable barrier here); the
    # fetch's flat round trip is MEASURED this run (fresh array per
    # trial — re-fetching a cached one times ~0) and subtracted.
    from bench import measure_fetch_overhead

    fetch_overhead = measure_fetch_overhead()
    results["fetch_overhead_ms"] = fetch_overhead * 1e3
    n_mb = method.num_rollouts // B
    steps = n_mb * method.ppo_epochs

    def stack_for(seed):
        r = np.random.default_rng(seed)
        fresh = mb.replace(
            response_tokens=jnp.asarray(
                r.integers(100, 40000, (B, R)), jnp.int32
            ),
            rewards=jnp.asarray(r.normal(size=(B, R)) * 0.1, jnp.float32),
        )
        return jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (steps,) + x.shape), fresh
        )

    # Three phase variants, INTERLEAVED across rounds (wall-clock swings
    # ±20% with shared-machine load — back-to-back A/Bs measured the GAE
    # hoist anywhere from 1.09x to 0.96x; interleaving is the defense):
    # - "hoisted": the shipped train_phase (GAE vmapped before the scan)
    # - "gae_in_scan": the pre-r5 program (GAE's sequential R-chain
    #   recomputed inside every scanned step), reconstructed by scanning
    #   the per-step program
    # - "chunked": train.logprob_chunk=16 on top of hoisted (the [B,R,V]
    #   f32 logits buffer never materializes; bwd recomputes chunks)
    old_phase = jax.jit(
        lambda s, mbs: jax.lax.scan(
            lambda s_, m: trainer._train_step_jit(s_, m), s, mbs
        ),
    )
    chunk_config = (
        _workload_config(2, None) if workload == "frozen_top2"
        else _workload_config(0, 2)
    )
    chunk_config.train.logprob_chunk = 16
    chunk_trainer = get_trainer(chunk_config.train.trainer)(
        chunk_config, reward_fn=lambda **kw: [0.0]
    )
    # each variant owns its state copy — the phase programs DONATE their
    # state argument, so sharing one tree across variants dies with
    # "Array has been deleted" on the second variant's warm call
    copy_state = lambda s: jax.tree_util.tree_map(jnp.copy, s)
    variants = {
        "hoisted": (trainer._train_phase_jit, copy_state(state)),
        "gae_in_scan": (old_phase, copy_state(state)),
        "chunked": (chunk_trainer._train_phase_jit, chunk_trainer.state),
    }

    def one_call(phase_fn, st, seed):
        # input prep (host RNG + device puts) stays OUTSIDE the window —
        # through this tunnel it costs the same order as the phase itself
        stk = jax.block_until_ready(stack_for(seed))
        t0 = time.time()
        st, stats = phase_fn(st, stk)
        float(np.asarray(jax.device_get(
            next(iter(jax.tree_util.tree_leaves(stats)))
        )).ravel()[0])
        return time.time() - t0 - fetch_overhead, st

    carries, best = {}, {}
    for name, (fn, st0) in variants.items():  # compile + warm each
        _, carries[name] = one_call(fn, st0, 0)
        best[name] = float("inf")
    for r in range(1, 5):  # 4 interleaved rounds
        for name, (fn, _) in variants.items():
            t, carries[name] = one_call(fn, carries[name], 100 * r)
            best[name] = min(best[name], t)

    results["train_phase_ms"] = best["hoisted"] * 1e3
    results["train_phase_per_step_ms"] = best["hoisted"] / steps * 1e3
    results["train_phase_gae_in_scan_ms"] = best["gae_in_scan"] * 1e3
    results["gae_hoist_speedup"] = round(
        best["gae_in_scan"] / best["hoisted"], 3
    )
    results["train_phase_chunked_logprob_ms"] = best["chunked"] * 1e3
    results["chunked_logprob_speedup"] = round(
        best["hoisted"] / best["chunked"], 3
    )
    del chunk_trainer, carries

    # --- component sum vs the real step
    results["component_sum_ms"] = (
        results["fwd_bwd_ms"] + results["optimizer_ms"]
    )
    results["unaccounted_ms_per_step"] = round(
        results["train_phase_per_step_ms"] - results["component_sum_ms"], 3
    )

    # --- FLOPs side: phase MFU at this shape
    _, train_flops = _phase_flops(
        d=d, V=V, L=L, Q=Q, R=R, B=method.num_rollouts,
        ppo_epochs=method.ppo_epochs,
        unfrozen=config.model.num_layers_unfrozen,
    )
    kind = jax.devices()[0].device_kind
    peak = BF16_PEAK_TFLOPS.get(kind, 0)
    step_flops = train_flops / steps
    results["train_step_tflops"] = round(step_flops / 1e12, 3)
    if peak:
        results["train_phase_mfu"] = round(
            step_flops / (results["train_phase_per_step_ms"] / 1e3)
            / 1e12 / peak, 4,
        )

    # --- HBM roofline: architecturally-required bytes per train step
    # (lower bound; fused activations uncounted) — delegated to bench.py's
    # `_train_step_bytes` (single byte model for artifact and audit: fwd
    # reads full weights, bwd pruned below the branch point, optimizer
    # traffic for the true trainable slice — unfrozen blocks + ln_f, the
    # mask freezes wte/wpe and the tied head)
    from bench import _train_step_bytes

    k_unfrozen = config.model.num_layers_unfrozen
    frac = k_unfrozen / L if 0 < k_unfrozen < L else 1.0
    blocks = L * (12 * d * d + 13 * d)
    head = V * d
    n_all = blocks + head + 2 * d
    trainable = n_all if frac == 1.0 else blocks * frac + 2 * d
    bytes_weights = (
        2 * (blocks + head + 2 * d)
        + 2 * (blocks * frac + head)
        + 4 * trainable
    )
    bytes_opt = 28 * trainable
    bytes_logits = 5 * B * R * V * 4
    bytes_acts = 2 * 2 * B * (Q + R) * d * (L * frac)
    step_bytes = _train_step_bytes(
        d=d, V=V, L=L, Q=Q, R=R, B=B, unfrozen=k_unfrozen
    )
    assert abs(
        step_bytes - (bytes_weights + bytes_opt + bytes_logits + bytes_acts)
    ) < 1e6  # the split must reconcile with the shared model
    results["workload"] = workload
    results["train_step_required_gb"] = round(step_bytes / 1e9, 3)
    results["bytes_split"] = {
        "weights_grads": round(bytes_weights / 1e9, 3),
        "optimizer": round(bytes_opt / 1e9, 3),
        "logits_pipeline": round(bytes_logits / 1e9, 3),
        "trunk_activations": round(bytes_acts / 1e9, 3),
    }
    hbm_peak = HBM_PEAK_GBPS.get(kind)
    if hbm_peak:
        gbps = step_bytes / (results["train_phase_per_step_ms"] / 1e3) / 1e9
        results["train_phase_hbm_gbps"] = round(gbps, 1)
        results["train_phase_hbm_util"] = round(gbps / hbm_peak, 4)
    results["device_kind"] = kind

    for k, v in list(results.items()):
        if isinstance(v, float):
            results[k] = round(v, 3)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
