"""A/B: chunked vs monolithic admission prefill, sharing off and on.

The PR's perf claim (docs/inference.md "Chunked prefill"): the engine's
monolithic ``[A, Q]`` prefill pays full prompt-capacity attention FLOPs
for every admitted row; the chunked program (``rollout.prefill_chunk``)
scans block-aligned prompt-column chunks under a ``lax.cond`` that skips
what no admitted row needs — leading pad columns of left-padded prompts,
and blocks served read-only from the shared-prefix pool — so prefill
compute scales with real prompt length, and prefix sharing becomes a
prefill-FLOP win (the docs/serving.md caveat, closed).

Methodology per the repo's measurement discipline: all four variants
run the SAME serving-style pump loop (plan-just-in-time admission,
harvest at fixed width), variants interleave across rounds (wall-clock
swings with machine load — A/B by alternation, never against recorded
numbers), and the CPU tier auto-shrinks the model: the CPU record
verifies bitwise parity + plumbing; the headline delta is a TPU
measurement (pending — this script self-records it on first hardware
run).

Four variants: {monolithic, chunked} x {sharing off, sharing on}.
Sharing-off batches use mixed-length left-padded prompts (the chunk
skip is the all-pad leading columns); sharing-on batches use
full-length prompts with a common leading half (the skip is the
pool-covered shared blocks — left-padded prompts share iff they pad
identically, docs/serving.md parity caveat).

Self-recording: updates ``AB_CHUNKED_PREFILL.json`` (latest record per
metric + device kind, ``utils/ab_record.py``) and appends a run-ledger
manifest (``telemetry/run_ledger.py``).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("WANDB_DISABLED", "1")

import numpy as np


def build_trainer():
    import jax

    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.utils.loading import get_trainer

    on_cpu = jax.default_backend() == "cpu"
    arch = (
        {"vocab_size": 512, "n_positions": 128, "n_embd": 64,
         "n_layer": 2, "n_head": 2}
        if on_cpu
        else {"vocab_size": 50257, "n_positions": 1024, "n_embd": 768,
              "n_layer": 12, "n_head": 12}
    )
    Q = 32 if on_cpu else 64
    R = 8 if on_cpu else 48
    rollout = (
        {"engine": "continuous", "slots": 16, "admit_width": 8,
         "harvest_width": 8, "block_size": 8}
        if on_cpu
        else {"engine": "continuous", "admit_width": 32,
              "harvest_width": 32, "block_size": 16}
    )
    config = TRLConfig.from_dict(
        {
            "model": {"model_type": "gpt2", "model_arch": arch},
            "train": {
                "seq_length": Q, "batch_size": 16, "epochs": 1,
                "total_steps": 10000, "eval_interval": 100000,
                "checkpoint_interval": 1000000,
                "mesh": {"dp": -1, "fsdp": 1, "tp": 1},
                "dtype": "bfloat16",
                "rollout": rollout,
            },
            "method": {
                "name": "PPOConfig", "num_rollouts": 128,
                "chunk_size": 128, "ppo_epochs": 4,
                "gen_kwargs": {
                    "max_new_tokens": R,
                    "min_new_tokens": R,
                    "top_k": 0,
                    "do_sample": True,
                    "eos_token_id": 511 if on_cpu else 50256,
                    "pad_token_id": 511 if on_cpu else 50256,
                },
            },
        }
    )
    return get_trainer(config.train.trainer)(
        config, reward_fn=lambda **kw: [0.0]
    )


def build_engines(trainer, prefill_chunk, pool_blocks):
    base = trainer.rollout_engine_obj
    return type(base)(
        apply_fn=base._apply_fn,
        init_cache_fn=base._init_cache_fn,
        gen_config=base.gen_config,
        query_length=base.Q,
        vocab_size=base.vocab_size,
        num_slots=base.num_slots,
        admit_width=base.admit_width,
        harvest_width=base.harvest_width,
        block_size=base.block_size,
        mesh=base.mesh,
        param_shardings=base._param_shardings,
        cache_sharding=base._cache_sharding,
        with_values=base.with_values,
        prefix_pool_blocks=pool_blocks,
        prefill_chunk=prefill_chunk,
    )


def make_prompts(rng, n, Q, vocab_hi, shared_prefix):
    """[n, Q] ids/mask. ``shared_prefix`` None: mixed-length left-padded
    rows (the pad-skip workload); else: full-length rows with a common
    leading half (the pool-skip workload — equal lengths so left-padded
    rows pad identically and the trie shares)."""
    ids = rng.integers(100, vocab_hi, (n, Q)).astype(np.int32)
    mask = np.ones((n, Q), np.int32)
    if shared_prefix is None:
        for i in range(n):
            real = int(rng.integers(4, Q + 1))
            mask[i, : Q - real] = 0
            ids[i, : Q - real] = 0
        # submit length-sorted: admit groups become length-homogeneous
        # (what a length-bucketing serving scheduler produces), so short
        # groups actually skip their leading all-pad chunks — the chunk
        # skip is a GROUP-max decision, and per-row RNG makes admission
        # order irrelevant to every row's bits (the engine contract)
        order = np.argsort(mask.sum(axis=1))
        ids, mask = ids[order], mask[order]
    else:
        ids[:, : len(shared_prefix)] = shared_prefix
    return ids, mask


def serve_rows(engine, ids, mask, pool=None):
    """Serving-style pump loop: plan-just-in-time admission in
    admit_width waves (a later wave's plan sees the earlier wave's
    published blocks as ready — the server's flow), pump to completion.
    Returns {row: tokens} host arrays. Pool refcounts are deliberately
    not released (the run ends; the pool is sized to never fill)."""
    N, fed = ids.shape[0], 0
    published_by_row = {}

    def on_admitted(rows):
        if pool is None:
            return
        for row in rows:
            blocks = published_by_row.pop(row, None)
            if blocks:
                pool.mark_ready(blocks)

    engine._admit_listener = on_admitted
    got = {}
    while len(got) < N:
        free = engine.free_capacity
        if fed < N and free > 0:
            take = min(free, engine.admit_width, N - fed)
            batch = slice(fed, fed + take)
            shared_maps = publish_maps = None
            if pool is not None:
                plans = [
                    pool.plan_admission(ids[i], mask[i])
                    for i in range(fed, fed + take)
                ]
                shared_maps = np.stack([p.shared_map for p in plans])
                publish_maps = np.stack([p.publish_map for p in plans])
            rows = engine.submit(
                ids[batch], mask[batch],
                shared_maps=shared_maps, publish_maps=publish_maps,
            )
            if pool is not None:
                for row, plan in zip(rows, plans):
                    if plan.published:
                        published_by_row[row] = plan.published
            fed += take
        for group in engine.pump():
            toks = np.asarray(group["tokens"])
            for j, r in enumerate(group["rows"]):
                got[r] = toks[j]
    return got


def main():
    import jax

    from trlx_tpu.serving.prefix_cache import PrefixBlockPool

    on_cpu = jax.default_backend() == "cpu"
    trainer = build_trainer()
    base = trainer.rollout_engine_obj
    Q = base.Q
    W = 8 if on_cpu else 16
    pool_blocks = 64
    vocab_hi = 500 if on_cpu else 40000
    N = 32 if on_cpu else 128
    rounds_n = 2 if on_cpu else 6

    engines = {
        "mono": build_engines(trainer, 0, 0),
        "chunked": build_engines(trainer, W, 0),
        "mono_shared": build_engines(trainer, 0, pool_blocks),
        "chunked_shared": build_engines(trainer, W, pool_blocks),
    }
    print(
        f"chunk width {engines['chunked'].prefill_chunk} "
        f"({engines['chunked'].n_prefill_chunks} chunks), "
        f"block {base.block_size}, Q={Q}",
        file=sys.stderr,
    )

    rng = np.random.default_rng(0)

    def measure(name, seed):
        engine = engines[name]
        shared = name.endswith("_shared")
        prng = np.random.default_rng(seed)
        prefix = (
            prng.integers(100, vocab_hi, Q // 2).astype(np.int32)
            if shared
            else None
        )
        ids, mask = make_prompts(prng, N, Q, vocab_hi, prefix)
        pool = (
            PrefixBlockPool(pool_blocks, engine.block_size, engine.n_blocks)
            if shared
            else None
        )
        trainer.rng = jax.random.PRNGKey(seed)
        trainer.reset_rollout_phase()
        engine.start_phase(
            trainer.rollout_params(), trainer.rollout_phase_key()
        )
        t0 = time.time()
        got = serve_rows(engine, ids, mask, pool)
        wall = time.time() - t0
        return wall, got, engine.stats

    # warm every compiled program, and pin CPU-tier bitwise parity on
    # the warming round (same seed per pair => same prompts + phase key)
    warm = {name: measure(name, 1234) for name in engines}
    for a, b in (("mono", "chunked"), ("mono_shared", "chunked_shared")):
        rows_a, rows_b = warm[a][1], warm[b][1]
        assert set(rows_a) == set(rows_b)
        for r in rows_a:
            np.testing.assert_array_equal(rows_a[r], rows_b[r])
    print("parity: chunked == monolithic tokens, sharing off AND on",
          file=sys.stderr)

    rounds = {name: [] for name in engines}
    order = list(engines)
    stats = {}
    for r in range(rounds_n):
        for name in order if r % 2 == 0 else reversed(order):
            wall, _, st = measure(name, 7 + r)
            rounds[name].append(wall)
            stats[name] = st
    med = {n: float(np.median(ts)) for n, ts in rounds.items()}
    for name, ts in rounds.items():
        print(
            f"{name}: median {med[name]*1e3:.1f} ms  "
            f"all {[round(x*1e3, 1) for x in ts]}",
            file=sys.stderr,
        )

    st_c, st_cs = stats["chunked"], stats["chunked_shared"]
    record = {
        "metric": (
            "chunked_prefill_serve_ms_cpu_tiny"
            if on_cpu
            else "chunked_prefill_serve_ms_B128_Q64_R48_gpt2s"
        ),
        **{f"{n}_ms": round(v * 1000, 1) for n, v in med.items()},
        "chunked_speedup": round(med["mono"] / med["chunked"], 3),
        "chunked_speedup_shared": round(
            med["mono_shared"] / med["chunked_shared"], 3
        ),
        "prefill_cols_skipped": int(st_c.prefill_cols_skipped),
        "prefill_flops_saved": float(st_c.prefill_flops_saved),
        "prefill_cols_skipped_shared": int(st_cs.prefill_cols_skipped),
        "prefill_flops_saved_shared": float(st_cs.prefill_flops_saved),
        "prefix_hit_rate_shared": round(st_cs.prefix_hit_rate, 4),
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(record))

    from trlx_tpu.utils.ab_record import record_latest

    record_latest(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "AB_CHUNKED_PREFILL.json"),
        record,
    )
    from trlx_tpu.telemetry.run_ledger import append_ab_manifest

    append_ab_manifest("ab_chunked_prefill", record)


if __name__ == "__main__":
    main()
