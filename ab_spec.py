"""A/B: trie-drafted speculative decoding in the continuous engine,
sharing off and on.

Supersedes the old stage-1 projection (acceptance probe + break-even
table): the drafted ``verify_step`` is now implemented
(docs/inference.md "Speculative decoding"), so this measures the real
thing. Four variants run the SAME serving-style pump loop:
{spec off, spec on} x {sharing off, sharing on}. Spec-off decodes one
token per jitted step; spec-on proposes up to ``max_draft`` host-drafted
tokens per slot (n-gram self-lookup; with sharing on, the shared-prefix
trie's ready chains as a global corpus) and verifies them in one batched
pass — accepted tokens are bitwise the tokens the one-token loop would
have sampled (the per-row RNG contract), which the warming round pins.

Methodology per the repo's measurement discipline: variants interleave
across rounds (wall-clock swings with machine load — A/B by alternation,
never against recorded numbers), and the CPU tier auto-shrinks the
model. The CPU record verifies bitwise parity + a nonzero accept-rate
with tokens-per-verify > 1; the headline wall delta is a TPU
measurement (direction 5a — this script self-records it on first
hardware run). Low temperature makes the workload draftable: near-greedy
decode on cyclic prompts falls into loops the n-gram drafter locks onto,
which is the regime speculation targets (templated/repetitive spans).

Self-recording: updates ``AB_SPEC.json`` (latest record per metric +
device kind, ``utils/ab_record.py``) and appends a run-ledger manifest
(``telemetry/run_ledger.py``).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("WANDB_DISABLED", "1")

import numpy as np

MAX_DRAFT = 4


def build_trainer():
    import jax

    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.utils.loading import get_trainer

    on_cpu = jax.default_backend() == "cpu"
    arch = (
        {"vocab_size": 512, "n_positions": 128, "n_embd": 64,
         "n_layer": 2, "n_head": 2}
        if on_cpu
        else {"vocab_size": 50257, "n_positions": 1024, "n_embd": 768,
              "n_layer": 12, "n_head": 12}
    )
    Q = 32 if on_cpu else 64
    R = 16 if on_cpu else 48
    rollout = (
        {"engine": "continuous", "slots": 16, "admit_width": 8,
         "harvest_width": 8, "block_size": 8}
        if on_cpu
        else {"engine": "continuous", "admit_width": 32,
              "harvest_width": 32, "block_size": 16}
    )
    config = TRLConfig.from_dict(
        {
            "model": {"model_type": "gpt2", "model_arch": arch},
            "train": {
                "seq_length": Q, "batch_size": 16, "epochs": 1,
                "total_steps": 10000, "eval_interval": 100000,
                "checkpoint_interval": 1000000,
                "mesh": {"dp": -1, "fsdp": 1, "tp": 1},
                "dtype": "bfloat16",
                "rollout": rollout,
            },
            "method": {
                "name": "PPOConfig", "num_rollouts": 128,
                "chunk_size": 128, "ppo_epochs": 4,
                "gen_kwargs": {
                    "max_new_tokens": R,
                    "min_new_tokens": R,
                    "top_k": 0,
                    "do_sample": True,
                    # near-greedy: random-init decode loops, so the
                    # drafter has something to accept (see docstring)
                    "temperature": 0.05,
                    "per_row_rng": True,
                    "eos_token_id": 511 if on_cpu else 50256,
                    "pad_token_id": 511 if on_cpu else 50256,
                },
            },
        }
    )
    return get_trainer(config.train.trainer)(
        config, reward_fn=lambda **kw: [0.0]
    )


def build_engines(trainer, spec_draft, pool_blocks):
    base = trainer.rollout_engine_obj
    return type(base)(
        apply_fn=base._apply_fn,
        init_cache_fn=base._init_cache_fn,
        gen_config=base.gen_config,
        query_length=base.Q,
        vocab_size=base.vocab_size,
        num_slots=base.num_slots,
        admit_width=base.admit_width,
        harvest_width=base.harvest_width,
        block_size=base.block_size,
        mesh=base.mesh,
        param_shardings=base._param_shardings,
        cache_sharding=base._cache_sharding,
        with_values=base.with_values,
        prefix_pool_blocks=pool_blocks,
        spec_max_draft=spec_draft,
    )


def make_prompts(rng, n, Q, shared_prefix):
    """[n, Q] ids/mask: cyclic two-token motifs (every suffix recurs, so
    the n-gram drafter has a match the moment decode starts looping).
    ``shared_prefix`` overwrites the leading half so the trie publishes
    common chains (the sharing-on workload); prompt identity between a
    spec/no-spec pair comes from the shared seed."""
    ids = np.zeros((n, Q), np.int32)
    for i in range(n):
        a = 3 + int(rng.integers(0, 4))
        b = 9 + int(rng.integers(0, 4))
        ids[i] = np.tile([a, b], (Q + 1) // 2)[:Q]
    mask = np.ones((n, Q), np.int32)
    if shared_prefix is not None:
        ids[:, : len(shared_prefix)] = shared_prefix
    return ids, mask


def serve_rows(engine, ids, mask, pool=None):
    """Serving-style pump loop: plan-just-in-time admission in
    admit_width waves, pump to completion. Returns {row: tokens} host
    arrays. Pool refcounts are deliberately not released (the run ends;
    the pool is sized to never fill)."""
    N, fed = ids.shape[0], 0
    published_by_row = {}

    def on_admitted(rows):
        if pool is None:
            return
        for row in rows:
            blocks = published_by_row.pop(row, None)
            if blocks:
                pool.mark_ready(blocks)

    engine._admit_listener = on_admitted
    got = {}
    while len(got) < N:
        free = engine.free_capacity
        if fed < N and free > 0:
            take = min(free, engine.admit_width, N - fed)
            batch = slice(fed, fed + take)
            shared_maps = publish_maps = None
            if pool is not None:
                plans = [
                    pool.plan_admission(ids[i], mask[i])
                    for i in range(fed, fed + take)
                ]
                shared_maps = np.stack([p.shared_map for p in plans])
                publish_maps = np.stack([p.publish_map for p in plans])
            rows = engine.submit(
                ids[batch], mask[batch],
                shared_maps=shared_maps, publish_maps=publish_maps,
            )
            if pool is not None:
                for row, plan in zip(rows, plans):
                    if plan.published:
                        published_by_row[row] = plan.published
            fed += take
        for group in engine.pump():
            toks = np.asarray(group["tokens"])
            for j, r in enumerate(group["rows"]):
                got[r] = toks[j]
    return got


def main():
    import jax

    from trlx_tpu.serving.prefix_cache import PrefixBlockPool
    from trlx_tpu.serving.spec_drafter import NGramDrafter, TrieDrafter

    on_cpu = jax.default_backend() == "cpu"
    trainer = build_trainer()
    base = trainer.rollout_engine_obj
    Q = base.Q
    pool_blocks = 64
    N = 32 if on_cpu else 128
    rounds_n = 2 if on_cpu else 6

    engines = {
        "base": build_engines(trainer, 0, 0),
        "spec": build_engines(trainer, MAX_DRAFT, 0),
        "base_shared": build_engines(trainer, 0, pool_blocks),
        "spec_shared": build_engines(trainer, MAX_DRAFT, pool_blocks),
    }
    print(
        f"max_draft {engines['spec'].spec_max_draft}, "
        f"block {base.block_size}, Q={Q}, R={base.R}",
        file=sys.stderr,
    )

    def measure(name, seed):
        engine = engines[name]
        shared = name.endswith("_shared")
        prng = np.random.default_rng(seed)
        prefix = (
            prng.integers(100, 500 if on_cpu else 40000, Q // 2)
            .astype(np.int32)
            if shared
            else None
        )
        ids, mask = make_prompts(prng, N, Q, prefix)
        pool = (
            PrefixBlockPool(pool_blocks, engine.block_size, engine.n_blocks)
            if shared
            else None
        )
        if engine.spec_max_draft:
            # fresh drafter per run: histories must not leak across
            # rounds (row ids restart each phase)
            engine.spec_drafter = (
                TrieDrafter(pool=pool, max_draft=engine.spec_max_draft)
                if pool is not None
                else NGramDrafter(max_draft=engine.spec_max_draft)
            )
        trainer.rng = jax.random.PRNGKey(seed)
        trainer.reset_rollout_phase()
        engine.start_phase(
            trainer.rollout_params(), trainer.rollout_phase_key()
        )
        t0 = time.time()
        got = serve_rows(engine, ids, mask, pool)
        wall = time.time() - t0
        return wall, got, engine.stats

    # warm every compiled program, and pin CPU-tier bitwise parity on
    # the warming round (same seed per pair => same prompts + phase key;
    # accepted tokens must be the tokens the one-token loop sampled)
    warm = {name: measure(name, 1234) for name in engines}
    for a, b in (("base", "spec"), ("base_shared", "spec_shared")):
        rows_a, rows_b = warm[a][1], warm[b][1]
        assert set(rows_a) == set(rows_b)
        for r in rows_a:
            np.testing.assert_array_equal(rows_a[r], rows_b[r])
    print("parity: spec == one-token-loop tokens, sharing off AND on",
          file=sys.stderr)

    rounds = {name: [] for name in engines}
    order = list(engines)
    stats = {}
    for r in range(rounds_n):
        for name in order if r % 2 == 0 else reversed(order):
            wall, _, st = measure(name, 7 + r)
            rounds[name].append(wall)
            stats[name] = st
    med = {n: float(np.median(ts)) for n, ts in rounds.items()}
    for name, ts in rounds.items():
        print(
            f"{name}: median {med[name]*1e3:.1f} ms  "
            f"all {[round(x*1e3, 1) for x in ts]}",
            file=sys.stderr,
        )

    st_s, st_ss = stats["spec"], stats["spec_shared"]
    record = {
        "metric": (
            "spec_decode_serve_ms_cpu_tiny"
            if on_cpu
            else "spec_decode_serve_ms_B128_Q64_R48_gpt2s"
        ),
        **{f"{n}_ms": round(v * 1000, 1) for n, v in med.items()},
        "spec_speedup": round(med["base"] / med["spec"], 3),
        "spec_speedup_shared": round(
            med["base_shared"] / med["spec_shared"], 3
        ),
        "max_draft": MAX_DRAFT,
        "accept_rate": round(st_s.spec_accept_rate, 4),
        "tokens_per_verify": round(st_s.spec_tokens_per_step, 4),
        "accept_rate_shared": round(st_ss.spec_accept_rate, 4),
        "tokens_per_verify_shared": round(st_ss.spec_tokens_per_step, 4),
        "verify_steps": int(st_s.spec_steps),
        "verify_steps_shared": int(st_ss.spec_steps),
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(record))

    assert record["accept_rate"] > 0, "CPU round must accept something"
    assert record["tokens_per_verify"] > 1, (
        "verify must average more than one committed token per step"
    )

    from trlx_tpu.utils.ab_record import record_latest

    record_latest(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "AB_SPEC.json"),
        record,
    )
    from trlx_tpu.telemetry.run_ledger import append_ab_manifest

    append_ab_manifest("ab_spec", record)


if __name__ == "__main__":
    main()
