"""A/B: multi-token / speculative decode on the pretrained stand-in
(VERDICT r4 #3 — the last structural collect-phase lever).

Decode is op-LATENCY-bound on this link (~1.5 ms/step at the bench shape
vs a ~0.5 ms traffic floor; ROADMAP "Round-4 perf findings" #3), which is
exactly the regime where speculative decoding pays: k cheap draft steps +
ONE full-model verify pass replace k sequential full steps, and the verify
pass (k tokens at once) costs about the same latency as a single-token
step.

Stage 1 (this file, always runs) — the math that decides viability without
building the sampler:

- **Acceptance probe.** For speculative sampling the per-position
  acceptance probability is EXACTLY ``sum_x min(p(x), q(x))`` (p = target,
  q = draft). We sample real rollouts from the locally-pretrained stand-in
  checkpoint (`ckpts/standin_gpt2`, real output distribution — the r4
  "random-init can't exercise acceptance" excuse does not apply here),
  then evaluate that sum at every response position for the natural
  self-draft: a 1-layer early exit reusing the target's own
  wte/wpe/h_0/ln_f/head (no separate draft training, no extra memory).
- **Latency probe.** Measured per-step latency of the draft (1-layer) vs
  target (2-layer) samplers at the reward-tier shape, chained inside one
  jit (tunnel methodology).
- **Projection.** Expected accepted tokens per round for k drafts is
  ``(1 - a^(k+1)) / (1 - a)`` (a = acceptance); round cost is
  ``k * t_draft + t_verify``. Speedup = tokens/round / (cost_round /
  t_target). Printed for k = 1..6 with the argmax.

Stage 2 (only if the projection clears 1.1x): implement the compiled
speculative sampler and measure end-to-end. If the projection is below
threshold, this file IS the measured-negative artifact — the methodology
and numbers say why the lever stays unpulled.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "examples")
)

K_RANGE = range(1, 7)


def main():
    os.environ.setdefault("WANDB_DISABLED", "1")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pretrained_standin import (
        causal_rl_config, ensure_gpt2_checkpoint, make_prompts,
    )
    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.utils.loading import get_trainer

    ckpt = ensure_gpt2_checkpoint()
    config = TRLConfig.from_dict(causal_rl_config(ckpt))
    trainer = get_trainer(config.train.trainer)(
        config, reward_fn=lambda **kw: [0.0]
    )
    gen = trainer.gen_config
    B, Q = 64, 8
    R = gen.max_new_tokens

    rng = np.random.default_rng(0)
    prompts = make_prompts(rng, B, Q)
    prompt_ids = jnp.asarray(
        [p + [0] * (Q - len(p)) for p in prompts], jnp.int32
    )[:, :Q]
    prompt_mask = jnp.ones((B, Q), jnp.int32)

    out = trainer.sample(prompt_ids, prompt_mask)
    full_ids = out.tokens  # [B, Q + R_eff] (R_eff = bound decode budget)
    R = full_ids.shape[1] - Q
    resp_mask = np.asarray(out.response_mask, bool)
    if resp_mask.shape[1] == full_ids.shape[1]:
        resp_mask = resp_mask[:, Q:]  # align with response positions

    backbone_params = trainer.state.params["transformer"]
    arch = trainer.model_config

    # target probs at response-predicting positions
    def probs_of(model, params):
        o = model.apply(
            {"params": params}, full_ids,
            attention_mask=jnp.ones_like(full_ids),
        )
        logits = o["logits"][:, Q - 1 : -1].astype(jnp.float32)
        if gen.temperature and gen.temperature != 1.0:
            logits = logits / gen.temperature
        return jax.nn.softmax(logits, axis=-1)

    from trlx_tpu.models.registry import get_model_family

    family = get_model_family("gpt2")
    target_probs = jax.jit(
        lambda p: probs_of(trainer.backbone, p)
    )(backbone_params)

    # self-draft: 1-layer early exit reusing wte/wpe/h_0/ln_f (+tied head)
    draft_arch = family.config_cls.from_dict(
        {**{k: getattr(arch, k) for k in (
            "vocab_size", "n_positions", "n_embd", "n_head",
        )}, "n_layer": 1, "dtype": arch.dtype}
    )
    draft_model = family.backbone_cls(draft_arch)
    draft_params = {
        k: backbone_params[k] for k in ("wte", "wpe", "h_0", "ln_f")
    }
    draft_probs = jax.jit(
        lambda p: probs_of(draft_model, p)
    )(draft_params)

    accept = jnp.sum(
        jnp.minimum(target_probs, draft_probs), axis=-1
    )  # [B, R]
    a = float(
        (np.asarray(accept) * resp_mask).sum() / max(resp_mask.sum(), 1)
    )

    # --- latency probe: chained decode steps inside one jit ------------
    from trlx_tpu.models.gpt2 import init_cache

    def step_latency(model, params, b, q, r):
        C = q + r
        cache = init_cache(model.config, b, C)
        ids0 = jnp.zeros((b, 1), jnp.int32)

        # params are an ARGUMENT, not a closure — closed-over arrays
        # serialize into the compile request and the tunnel rejects the
        # 124M-param program body (HTTP 413)
        def run(p, ids, cache):
            def body(carry, _):
                ids, cache = carry
                o = model.apply(
                    {"params": p}, ids,
                    attention_mask=jnp.ones((b, C), jnp.int32),
                    cache=cache, cache_index=jnp.int32(q),
                )
                nxt = jnp.argmax(
                    o["logits"][:, -1], axis=-1
                )[:, None].astype(jnp.int32)
                return (nxt, o["cache"]), None

            (ids, cache), _ = jax.lax.scan(
                body, (ids, cache), None, length=50
            )
            return ids

        fn = jax.jit(run)
        out0 = fn(params, ids0, cache)
        jax.block_until_ready(out0)
        best = float("inf")
        for _ in range(3):
            t0 = time.time()
            jax.block_until_ready(fn(params, ids0, cache))
            best = min(best, time.time() - t0)
        return best / 50

    t_target = step_latency(trainer.backbone, backbone_params, B, Q, R)
    t_draft = step_latency(draft_model, draft_params, B, Q, R)
    # verify pass = one full-model forward over k+1 tokens with cache —
    # latency-bound, so approximate with the measured single-step target
    # latency (k tokens widen an already tiny matmul)
    t_verify = t_target

    proj = {}
    for k in K_RANGE:
        tokens = (1 - a ** (k + 1)) / (1 - a) if a < 1 else k + 1
        cost = k * t_draft + t_verify
        proj[k] = tokens / (cost / t_target)
    best_k = max(proj, key=proj.get)

    result = {
        "acceptance_rate": round(a, 4),
        "t_target_ms": round(t_target * 1e3, 3),
        "t_draft_ms": round(t_draft * 1e3, 3),
        "projected_speedup_by_k": {k: round(v, 3) for k, v in proj.items()},
        "best_k": best_k,
        "best_projected_speedup": round(proj[best_k], 3),
        "verdict": (
            "IMPLEMENT stage 2" if proj[best_k] > 1.1 else
            "NEGATIVE: projection below 1.1x — lever stays unpulled"
        ),
    }

    # --- the other half: latency ratio at the BENCH workload shape.
    # Acceptance there is unmeasurable without a real checkpoint
    # (random-init distributions are meaningless), but the draft/target
    # latency ratio rho IS measurable, and with it the BREAK-EVEN
    # acceptance curve: speculation wins iff
    # (1 - a^(k+1)) / (1 - a) > k*rho + 1.
    from bench import _workload_config
    from trlx_tpu.models.registry import get_model_family as _fam

    # the EXACT bench workload arch (single source of truth) + a 2-layer
    # shared-weight draft of it
    bench_arch_dict = dict(
        _workload_config(0, 2).model.model_arch, dtype="bfloat16"
    )
    bench_arch = _fam("gpt2").config_cls.from_dict(bench_arch_dict)
    bench_model = _fam("gpt2").backbone_cls(bench_arch)
    draft2_arch = _fam("gpt2").config_cls.from_dict(
        dict(bench_arch_dict, n_layer=2)
    )
    draft2_model = _fam("gpt2").backbone_cls(draft2_arch)
    rngk = jax.random.PRNGKey(0)
    dummy = jnp.ones((2, 4), jnp.int32)
    bench_params = bench_model.init(
        rngk, dummy, attention_mask=jnp.ones_like(dummy)
    )["params"]
    draft2_params = {
        k: bench_params[k] for k in ("wte", "wpe", "h_0", "h_1", "ln_f")
    }

    t_bench_target = step_latency(bench_model, bench_params, 128, 64, 48)
    t_bench_draft = step_latency(draft2_model, draft2_params, 128, 64, 48)
    rho = t_bench_draft / t_bench_target

    def break_even_acceptance(k, rho):
        lo, hi = 0.0, 1.0
        for _ in range(40):
            mid = (lo + hi) / 2
            tokens = (k + 1) if mid >= 1 else (1 - mid ** (k + 1)) / (1 - mid)
            if tokens > k * rho + 1:
                hi = mid
            else:
                lo = mid
        return hi

    result.update(
        {
            "bench_shape_t_target_ms": round(t_bench_target * 1e3, 3),
            "bench_shape_t_draft2_ms": round(t_bench_draft * 1e3, 3),
            "bench_shape_rho": round(rho, 3),
            "bench_shape_break_even_acceptance_by_k": {
                k: round(break_even_acceptance(k, rho), 3) for k in K_RANGE
            },
        }
    )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
