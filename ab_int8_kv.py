"""A/B: int8 vs bf16 rollout KV cache on the bench workload (real TPU).

Methodology per the repo's measurement discipline: per measurement, queue
K sampler dispatches on DISTINCT inputs (execution caching makes repeated
identical calls free), force with ONE summed fetch (~110 ms flat), and
interleave variants across rounds (wall-clock swings ±20% with machine
load, so A/B by alternation, never against recorded numbers).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("WANDB_DISABLED", "1")

import numpy as np


def build_trainer(kv_dtype):
    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.utils.loading import get_trainer

    config = TRLConfig.from_dict(
        {
            "model": {
                "model_type": "gpt2",
                "model_arch": {
                    "vocab_size": 50257, "n_positions": 1024, "n_embd": 768,
                    "n_layer": 12, "n_head": 12, "kv_cache_dtype": kv_dtype,
                },
            },
            "train": {
                "seq_length": 64, "batch_size": 16, "epochs": 1,
                "total_steps": 10000, "eval_interval": 100000,
                "checkpoint_interval": 1000000,
                "mesh": {"dp": -1, "fsdp": 1, "tp": 1}, "dtype": "bfloat16",
            },
            "method": {
                "name": "PPOConfig", "num_rollouts": 128, "chunk_size": 128,
                "ppo_epochs": 4,
                "gen_kwargs": {
                    "max_new_tokens": 48, "min_new_tokens": 48, "top_k": 0,
                    "do_sample": True, "eos_token_id": 50256,
                    "pad_token_id": 50256,
                },
            },
        }
    )
    return get_trainer(config.train.trainer)(
        config, reward_fn=lambda **kw: [0.0]
    )


def main():
    import jax
    import jax.numpy as jnp

    B, Q, K = 128, 64, 10
    rng = np.random.default_rng(0)

    def fresh_batches(n):
        return [
            (
                jnp.asarray(rng.integers(100, 40000, (B, Q)), jnp.int32),
                jnp.ones((B, Q), jnp.int32),
            )
            for _ in range(n)
        ]

    trainers = {"bf16": build_trainer("bfloat16"), "int8": build_trainer("int8")}

    def measure(trainer, batches):
        t0 = time.time()
        acc = jnp.zeros((), jnp.int32)
        for ids, mask in batches:
            out = trainer.sample(ids, mask)
            acc = acc + out.tokens.sum()
        _ = int(acc)  # single forcing fetch
        return time.time() - t0

    # warm both compiled samplers (distinct signatures)
    for t in trainers.values():
        measure(t, fresh_batches(1))

    rounds = {"bf16": [], "int8": []}
    for r in range(6):
        for name in ("bf16", "int8") if r % 2 == 0 else ("int8", "bf16"):
            rounds[name].append(measure(trainers[name], fresh_batches(K)))
    for name, ts in rounds.items():
        per_call = [(t - 0.11) / K for t in ts]
        print(
            f"{name}: per-sampler-call mean {np.mean(per_call)*1e3:.1f} ms  "
            f"median {np.median(per_call)*1e3:.1f} ms  "
            f"all {[round(x*1e3, 1) for x in per_call]}"
        )
    speedup = np.median(rounds["bf16"]) / np.median(rounds["int8"])
    print(f"int8 speedup over bf16 (median-of-rounds): {speedup:.3f}x")


if __name__ == "__main__":
    main()
