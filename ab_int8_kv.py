"""A/B: int8 vs bf16 rollout KV cache, on both rollout engines (TPU).

Methodology per the repo's measurement discipline: per measurement, queue
K sampler dispatches on DISTINCT inputs (execution caching makes repeated
identical calls free), force with ONE summed fetch (~110 ms flat), and
interleave variants across rounds (wall-clock swings ±20% with machine
load, so A/B by alternation, never against recorded numbers).

Four variants: {bf16, int8} × {fixed sampler, continuous engine}. The
int8 lever now routes through BOTH cache layouts — the linear buffers
(``models/gpt2.py::kv_buffers``) and the paged/block cache the
continuous engine decodes over (``inference/kv_cache.py``: quantize on
write through the block table, dequantize the gathered logical view).

Self-recording (the AB_PHASE_OVERLAP.json pattern): every run updates
``AB_INT8_KV.json`` at the repo root with the latest record per
(metric, device kind) — the first hardware run lands the TPU delta in a
committed artifact automatically. On a CPU backend the model shrinks
(gpt2-small decode is minutes/call on CPU): the CPU record verifies
parity + plumbing; the headline delta is a TPU measurement.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("WANDB_DISABLED", "1")

import numpy as np


def build_trainer(kv_dtype, engine):
    import jax

    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.utils.loading import get_trainer

    on_cpu = jax.default_backend() == "cpu"
    arch = (
        {"vocab_size": 512, "n_positions": 128, "n_embd": 64,
         "n_layer": 2, "n_head": 2}
        if on_cpu
        else {"vocab_size": 50257, "n_positions": 1024, "n_embd": 768,
              "n_layer": 12, "n_head": 12}
    )
    # engine geometry must fit the measured batch width B: slots default
    # to chunk_size (128), whose default harvest_width (32) exceeds the
    # CPU shrink's 16-row batches — drive() would floor the target to 0
    # and the engine variants would never decode a token
    rollout = (
        {"engine": engine, "slots": 16, "admit_width": 8,
         "harvest_width": 8, "block_size": 8}
        if on_cpu
        else {"engine": engine, "admit_width": 32, "harvest_width": 32}
    )
    config = TRLConfig.from_dict(
        {
            "model": {
                "model_type": "gpt2",
                "model_arch": dict(arch, kv_cache_dtype=kv_dtype),
            },
            "train": {
                "seq_length": 64, "batch_size": 16, "epochs": 1,
                "total_steps": 10000, "eval_interval": 100000,
                "checkpoint_interval": 1000000,
                "mesh": {"dp": -1, "fsdp": 1, "tp": 1}, "dtype": "bfloat16",
                "rollout": rollout,
            },
            "method": {
                "name": "PPOConfig", "num_rollouts": 128, "chunk_size": 128,
                "ppo_epochs": 4,
                "gen_kwargs": {
                    "max_new_tokens": 8 if on_cpu else 48,
                    "min_new_tokens": 8 if on_cpu else 48,
                    "top_k": 0,
                    "do_sample": True,
                    "eos_token_id": 511 if on_cpu else 50256,
                    "pad_token_id": 511 if on_cpu else 50256,
                },
            },
        }
    )
    return get_trainer(config.train.trainer)(
        config, reward_fn=lambda **kw: [0.0]
    )


def main():
    import jax
    import jax.numpy as jnp

    on_cpu = jax.default_backend() == "cpu"
    B, Q = (16, 64) if on_cpu else (128, 64)
    K = 2 if on_cpu else 10
    rounds_n = 2 if on_cpu else 6
    rng = np.random.default_rng(0)
    vocab_hi = 500 if on_cpu else 40000

    def fresh_batches(n):
        return [
            (
                jnp.asarray(rng.integers(100, vocab_hi, (B, Q)), jnp.int32),
                jnp.ones((B, Q), jnp.int32),
            )
            for _ in range(n)
        ]

    trainers = {
        "bf16": build_trainer("bfloat16", "fixed"),
        "int8": build_trainer("int8", "fixed"),
        "bf16_engine": build_trainer("bfloat16", "continuous"),
        "int8_engine": build_trainer("int8", "continuous"),
    }

    def measure_fixed(trainer, batches):
        t0 = time.time()
        acc = jnp.zeros((), jnp.int32)
        for ids, mask in batches:
            out = trainer.sample(ids, mask)
            acc = acc + out.tokens.sum()
        _ = int(acc)  # single forcing fetch
        return time.time() - t0

    def measure_engine(trainer, batches):
        """Continuous engine: same prompt volume through the slot loop
        (admission/decode/harvest included — this IS the engine's cost
        model, unlike the fixed path where scoring overlaps)."""
        engine = trainer.rollout_engine_obj
        t0 = time.time()
        total = 0
        for ids, mask in batches:
            trainer.reset_rollout_phase()
            engine.start_phase(
                trainer.rollout_params(), trainer.rollout_phase_key()
            )
            n = ids.shape[0]
            engine.submit(np.asarray(ids), np.asarray(mask))
            target = (n // engine.harvest_width) * engine.harvest_width
            if target < n:
                raise RuntimeError(
                    f"engine harvest_width {engine.harvest_width} does "
                    f"not fit the {n}-row batch — the measurement would "
                    "drop rows (or decode nothing at all)"
                )
            for group in engine.drive(target):
                total += int(np.asarray(group["tokens"]).shape[0])
        if total != len(batches) * n:
            raise RuntimeError("engine completed fewer rows than submitted")
        return time.time() - t0

    def measure(name, batches):
        trainer = trainers[name]
        if name.endswith("_engine"):
            return measure_engine(trainer, batches)
        return measure_fixed(trainer, batches)

    # warm every compiled program (distinct signatures)
    for name in trainers:
        measure(name, fresh_batches(1))

    rounds = {name: [] for name in trainers}
    order = list(trainers)
    for r in range(rounds_n):
        for name in order if r % 2 == 0 else reversed(order):
            rounds[name].append(measure(name, fresh_batches(K)))
    fetch_overhead = 0.0 if on_cpu else 0.11  # tunneled-TPU fetch cost
    for name, ts in rounds.items():
        per_call = [(t - fetch_overhead) / K for t in ts]
        print(
            f"{name}: per-call mean {np.mean(per_call)*1e3:.1f} ms  "
            f"median {np.median(per_call)*1e3:.1f} ms  "
            f"all {[round(x*1e3, 1) for x in per_call]}"
        )

    # the RECORDED per-call ms uses the same definition as the printed
    # lines (fetch overhead subtracted), so artifact and console agree.
    # Engine variants additionally pay per-step done-flag fetches — that
    # is part of the engine's real cost model, deliberately included.
    med = {
        name: (float(np.median(ts)) - fetch_overhead) / K
        for name, ts in rounds.items()
    }
    record = {
        "metric": (
            "int8_kv_sampler_ms_B128_Q64_R48_gpt2s"
            if not on_cpu else "int8_kv_sampler_ms_cpu_tiny"
        ),
        **{f"{name}_ms": round(v * 1000, 1) for name, v in med.items()},
        "int8_speedup_fixed": round(med["bf16"] / med["int8"], 3),
        "int8_speedup_engine": round(
            med["bf16_engine"] / med["int8_engine"], 3
        ),
        "engine_vs_fixed_bf16": round(med["bf16"] / med["bf16_engine"], 3),
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(record))

    from trlx_tpu.utils.ab_record import record_latest

    record_latest(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "AB_INT8_KV.json"),
        record,
    )
    # run-ledger history next to the latest-per-key artifact
    from trlx_tpu.telemetry.run_ledger import append_ab_manifest

    append_ab_manifest("ab_int8_kv", record)


if __name__ == "__main__":
    main()
