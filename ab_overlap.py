"""A/B: overlapped collect orchestration vs the reference's serial order.

The orchestrator overlaps the host boundary three ways (VERDICT r3 #1;
`orchestrator/ppo_orchestrator.py::_dispatch_chunk`):

1. the frozen-ref forward is dispatched right behind the sampler, so it
   executes on device DURING the token fetch + host scoring;
2. the sampler outputs start their device->host copy at dispatch time
   (``copy_to_host_async``), overlapping the transfer with the ref exec;
3. the rollout KL stays a device scalar (fetching it per chunk would add
   a ~100ms round-trip on a tunneled chip).

The serial variant reproduces the reference's sequence
(`ppo_orchestrator.py:74-151`): generate -> fetch -> decode -> score ->
THEN the ref/recompute forwards -> rewards. Same compiled programs, same
shapes — only the dispatch order differs.

A third variant splits the phase into 2 chunks of 64 (the pipelining the
orchestrator does when num_rollouts > chunk_size): on a LOW-LATENCY host
link chunking hides the per-chunk host tail behind the next chunk's
decode; through this tunnel's flat ~100ms round-trip it measures as a
wash-to-loss — each extra chunk adds a full fetch latency that the
halved decode time cannot cover. Documented here so the single-fetch
default is a measured choice, not an assumption.

Methodology per bench_longctx.py / MEMORY.md: compile warmup first, fresh
sampler rng per call (inputs always distinct), variants interleaved across
rounds (shared-chip load swings +-20%), best-of-N, one forcing fetch per
timed region.

Prints one JSON line with per-variant best ms and the speedup.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("WANDB_DISABLED", "1")

import jax
import jax.numpy as jnp
import numpy as np

from bench_collect_audit import (
    bench_reward_fn as reward_fn, force, make_bench_workload,
)
from trlx_tpu.utils.loading import get_orchestrator


def main():
    config, trainer, pipeline, orch = make_bench_workload()
    orch_chunked = get_orchestrator(config.train.orchestrator)(
        trainer, pipeline, reward_fn=reward_fn, chunk_size=64
    )
    loader = iter(pipeline.create_loader(128, shuffle=True, seed=1))

    def overlapped():
        trainer.buffer.clear_history()
        orch.make_experience(config.method.num_rollouts, 0)
        force(trainer.buffer._chunks[-1].rewards)

    def chunked():
        trainer.buffer.clear_history()
        orch_chunked.make_experience(config.method.num_rollouts, 0)
        force(trainer.buffer._chunks[-1].rewards)

    def serial():
        """Reference dispatch order: nothing queued behind the sampler."""
        nonlocal loader
        trainer.buffer.clear_history()
        try:
            batch, meta = next(loader)
        except StopIteration:
            loader = iter(pipeline.create_loader(128, shuffle=True, seed=2))
            batch, meta = next(loader)
        so = trainer.sample(batch.input_ids, batch.attention_mask)
        toks, mask = jax.device_get((so.tokens, so.response_mask))
        texts = trainer.decode_responses(toks, mask)
        scores = np.asarray(reward_fn(texts, None), dtype=np.float32)
        ref = trainer.score_ref(
            batch.input_ids, batch.attention_mask, so.tokens, so.response_mask
        )
        rewards = trainer.compute_rewards(
            so.logprobs, ref, so.response_mask, scores
        )
        force(rewards)

    variants = {"overlapped": overlapped, "serial": serial, "chunked": chunked}
    for fn in variants.values():  # compile warmup
        fn()

    best = {k: float("inf") for k in variants}
    order = list(variants)
    for rnd in range(4):
        for k in order if rnd % 2 == 0 else reversed(order):
            t0 = time.perf_counter()
            variants[k]()
            best[k] = min(best[k], (time.perf_counter() - t0) * 1000)

    print(json.dumps({
        "metric": "collect_phase_ms_B128_Q64_R48_gpt2s",
        **{f"{k}_ms": round(v, 1) for k, v in best.items()},
        "overlap_speedup_vs_serial": round(best["serial"] / best["overlapped"], 3),
        "chunked_vs_single_fetch": round(best["chunked"] / best["overlapped"], 3),
        "device_kind": jax.devices()[0].device_kind,
    }))


if __name__ == "__main__":
    main()
