"""Long-context hardware measurements on the real chip (VERDICT r2 #5).

Puts measured numbers behind the long-context claims that round 2 verified
only via compiled-HLO inspection:

1. ``train_step``  — full gpt2-small LM fwd+bwd+AdamW step at T=1024/2048/4096
   with the flash kernel engaged vs the XLA einsum path (token budget held
   constant at B*T = 8192).
2. ``attn_kernel`` — isolated causal attention fwd+bwd at the same shapes
   plus 8k, flash vs XLA.
3. ``decode``      — compiled sampler at a 2048-token prompt, bf16 vs int8
   KV cache: per-generated-token cost (R=16 vs R=64 differencing).
4. ``ring_sp2``    — the sp=2 ring-attention *per-device critical path*
   compute at T=4096 measured single-chip (the lagging device's two
   2048x2048 blocks), vs the full-T single-device cost. ICI overlap cost is
   NOT measurable on one chip; this grounds the compute half of the ring
   claim and is labeled as such.

Methodology (per `ab_int8_kv.py`'s measurement discipline): compile every
variant ONCE up front; each timed call runs on FRESH inputs (the tunnel's
execution cache makes repeated identical calls free, which poisons naive
repeats); iterations are chained inside one jit (lax.scan) with a single
forcing fetch (~110 ms flat, subtracted); variants are interleaved across
rounds because wall-clock swings ±20% with machine load. OOM on the XLA
path is caught and recorded as a result ("oom"), not an error: flash
running where XLA cannot is the point.

Writes LONGCTX.json and prints one JSON line per measurement.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import trlx_tpu.ops.attention as attention_mod
from trlx_tpu.models.gpt2 import GPT2Config, GPT2Model, init_cache
from trlx_tpu.ops.sampling import GenerationConfig, make_sampler

FLASH_DEFAULT = attention_mod.FLASH_MIN_SEQ
XLA_ONLY = 1 << 30
FETCH_OVERHEAD_S = 0.11  # flat per-blocking-call tunnel cost
ROUNDS = 3


def _set_mode(mode: str):
    attention_mod.FLASH_MIN_SEQ = FLASH_DEFAULT if mode == "flash" else XLA_ONLY


def _is_oom(e: Exception) -> bool:
    s = str(e)
    return "RESOURCE_EXHAUSTED" in s or "memory" in s.lower()


def interleaved_rounds(variants, rounds=ROUNDS):
    """variants: {name: (thunk(rng_round) -> seconds)}. Compiles are the
    caller's problem (warm up before calling). Returns {name: best_seconds},
    alternating order across rounds so load swings hit both variants."""
    times = {name: [] for name in variants}
    names = list(variants)
    for r in range(rounds):
        order = names if r % 2 == 0 else names[::-1]
        for name in order:
            times[name].append(variants[name](r))
    return {name: min(ts) for name, ts in times.items()}


# --------------------------- train step --------------------------------- #


def _delete_tree(tree):
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "delete"):
            leaf.delete()


def measure_train_steps(rng):
    """Per T: ONE params+opt_state (mode-independent, same seed) shared by
    both mode thunks — the HBM is too small for two f32 master+Adam copies
    alongside 2k-context XLA attention temps — and explicit buffer deletion
    between T's (accumulated live buffers OOM'd the run otherwise)."""
    out = []
    cfg = GPT2Config(
        vocab_size=50257, n_positions=4096, n_embd=768, n_layer=12, n_head=12
    )
    model = GPT2Model(cfg)
    tx = optax.adamw(1e-4)

    def make_run():
        # a FRESH function object per (T, mode): jax.jit keys its global
        # trace cache on the underlying callable, so a shared `run` would
        # silently reuse the first mode's compiled program for both
        def loss_fn(params, ids):
            o = model.apply({"params": params}, ids)
            lp = jax.nn.log_softmax(o["logits"][:, :-1], axis=-1)
            ll = jnp.take_along_axis(lp, ids[:, 1:, None], axis=-1)[..., 0]
            return -jnp.mean(ll)

        def step(carry, ids):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, ids)
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        def run(carry, xs):
            _, losses = jax.lax.scan(step, carry, xs)
            return jnp.sum(losses)

        return run

    for T in (1024, 2048, 4096):
        B = max(8192 // T, 1)
        K = 8
        ids0 = jnp.asarray(rng.integers(0, 50000, size=(B, T)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), ids0)["params"]
        opt_state = tx.init(params)

        def fresh(seed):
            x = jnp.asarray(
                np.random.default_rng(seed).integers(
                    0, 50000, size=(K, B, T)
                ),
                jnp.int32,
            )
            return jax.block_until_ready(x)

        variants = {}
        status = {}
        for mode in ("flash", "xla"):
            _set_mode(mode)
            fn = jax.jit(make_run())  # fresh callable per mode (see above)
            try:
                # real fetch: on the tunneled backend only a device->host
                # transfer forces execution
                float(fn((params, opt_state), fresh(10_000)))
            except Exception as e:
                if _is_oom(e):
                    status[mode] = "oom"
                    continue
                raise

            def thunk(r, fn=fn, mode=mode):
                _set_mode(mode)
                xs = fresh(20_000 + r)
                t0 = time.perf_counter()
                float(fn((params, opt_state), xs))
                return time.perf_counter() - t0

            variants[mode] = thunk
        best = interleaved_rounds(variants) if variants else {}
        for m in ("flash", "xla"):
            if m in status:
                rec = {"T": T, "B": B, "mode": m, "result": status[m]}
            else:
                sec = (best[m] - FETCH_OVERHEAD_S) / K
                rec = {
                    "T": T, "B": B, "mode": m,
                    "ms_per_step": round(sec * 1e3, 2),
                    "tok_per_sec": round(B * T / sec, 0),
                }
            out.append(rec)
            print(json.dumps({"measurement": "train_step", **rec}))
        _delete_tree((params, opt_state, ids0))
    return out


# --------------------------- attention kernel ---------------------------- #


def build_attn(T, mode, rng, B=4, H=12, D=64, K=None, composite=None):
    """thunk(round) -> seconds for K chained causal-attn fwd+bwd, or "oom".
    ``composite`` overrides the per-item forward (used by ring_sp2).
    K scales inversely with T so small shapes amortize the ~110 ms fetch."""
    _set_mode(mode)
    if K is None:
        K = max(4, (4 * 4096) // T)

    def fwd(args):
        q, k, v = args
        return jnp.sum(
            attention_mod.dot_product_attention(q, k, v, causal=True).astype(
                jnp.float32
            )
        )

    fwd = composite or fwd

    def step(carry, xs):
        val, grads = jax.value_and_grad(fwd)(xs)
        return carry, val + sum(jnp.sum(g.astype(jnp.float32)) for g in grads)

    def run(carry, xs):
        _, vals = jax.lax.scan(step, carry, xs)
        return jnp.sum(vals)

    fn = jax.jit(run)

    def fresh(seed):
        r = np.random.default_rng(seed)
        xs = tuple(
            jnp.asarray(r.standard_normal((K, B, T, H, D)), jnp.bfloat16)
            for _ in range(3)
        )
        return jax.tree_util.tree_map(jax.block_until_ready, xs)

    try:
        float(fn(0.0, fresh(30_000 + T)))  # real fetch forces execution
    except Exception as e:
        if _is_oom(e):
            return "oom", K
        raise

    def thunk(r):
        xs = fresh(40_000 + 10 * T + r)
        t0 = time.perf_counter()
        float(fn(0.0, xs))
        return time.perf_counter() - t0

    return thunk, K


def measure_attn_kernels(rng):
    out = []
    for T in (1024, 2048, 4096, 8192):
        built = {m: build_attn(T, m, rng) for m in ("flash", "xla")}
        variants = {
            m: t for m, (t, _) in built.items() if not isinstance(t, str)
        }
        best = interleaved_rounds(variants) if variants else {}
        for m, (t, K) in built.items():
            if isinstance(t, str):
                rec = {"T": T, "B": 4, "mode": m, "result": t}
            else:
                sec = (best[m] - FETCH_OVERHEAD_S) / K
                rec = {
                    "T": T, "B": 4, "mode": m,
                    "ms_per_fwdbwd": round(sec * 1e3, 3),
                }
            out.append(rec)
            print(json.dumps({"measurement": "attn_kernel", **rec}))
    return out


# ------------------------------- decode ---------------------------------- #


def build_decode(kv_dtype, R, rng, params, B=8, Q=2048):
    """thunk(round) -> seconds per sampler call (fetch-corrected): CALLS=3
    chained distinct-prompt sampler dispatches, one forcing fetch. ``params``
    are shared across all four variants (identical seed; one f32 copy in
    HBM instead of four)."""
    _set_mode("flash")
    CALLS = 3
    cfg = GPT2Config(
        vocab_size=50257, n_positions=4096, n_embd=768, n_layer=12,
        n_head=12, kv_cache_dtype=kv_dtype,
    )
    model = GPT2Model(cfg)

    def apply_fn(params, input_ids, attention_mask=None, position_ids=None,
                 cache=None, cache_index=None):
        return model.apply(
            {"params": params}, input_ids, attention_mask=attention_mask,
            position_ids=position_ids, cache=cache, cache_index=cache_index,
        )

    gen = GenerationConfig(
        max_new_tokens=R, min_new_tokens=R, do_sample=True, top_k=0,
        eos_token_id=50256, pad_token_id=50256,
    )
    sampler = jax.jit(
        make_sampler(apply_fn, lambda b, cap: init_cache(cfg, b, cap),
                     gen, Q, with_values=False)
    )
    mask = jnp.ones((B, Q), jnp.int32)

    def fresh(seed, n=CALLS):
        r = np.random.default_rng(seed)
        return [
            jax.block_until_ready(
                jnp.asarray(r.integers(0, 50000, size=(B, Q)), jnp.int32)
            )
            for _ in range(n)
        ]

    int(sampler(
        params, fresh(50_000, 1)[0], mask, jax.random.PRNGKey(0)
    ).tokens.sum())  # real fetch forces execution

    def thunk(r):
        prompts = fresh(60_000 + 100 * R + r)
        t0 = time.perf_counter()
        acc = jnp.zeros((), jnp.int32)
        for i, p in enumerate(prompts):
            acc = acc + sampler(
                params, p, mask, jax.random.PRNGKey(1000 * r + i)
            ).tokens.sum()
        int(acc)  # single forcing fetch
        return (time.perf_counter() - t0 - FETCH_OVERHEAD_S) / CALLS

    return thunk


def measure_decode(rng):
    out = []
    cfg = GPT2Config(
        vocab_size=50257, n_positions=4096, n_embd=768, n_layer=12, n_head=12
    )
    ids0 = jnp.asarray(rng.integers(0, 50000, size=(1, 8)), jnp.int32)
    params = GPT2Model(cfg).init(jax.random.PRNGKey(0), ids0)["params"]
    variants = {}
    for kv in ("bfloat16", "int8"):
        for R in (16, 64):
            variants[f"{kv}/{R}"] = build_decode(kv, R, rng, params)
    best = interleaved_rounds(variants)
    _delete_tree((params, ids0))
    for kv in ("bfloat16", "int8"):
        t16, t64 = best[f"{kv}/16"], best[f"{kv}/64"]
        per_tok = (t64 - t16) / 48
        rec = {
            "B": 8, "prompt_len": 2048, "kv_cache_dtype": kv,
            "ms_per_decode_token": round(per_tok * 1e3, 3),
            "sampler_call_s_R16": round(t16, 4),
            "sampler_call_s_R64": round(t64, 4),
        }
        out.append(rec)
        print(json.dumps({"measurement": "decode", **rec}))
    return out


def measure_sp_decode(rng):
    """sp=2 sharded-cache decode, single-chip critical path (VERDICT r4
    #5, r3 weak #6 — the row LONGCTX never had; the real sp-mesh decode
    program is exercised by tests/test_sp_decode.py on the virtual mesh).

    Under sp, each device holds C/sp KV-cache positions; a decode step
    attends the current token to the local shard and the devices combine
    softmax stats (psum). Single-chip measurable: the per-device shard
    attention — decode at a 1024-position cache (the sp=2 shard of the
    2048 prompt) vs the full 2048 cache, per-generated-token cost by
    R=16/64 differencing. The stats-combine + ICI hop is excluded, so
    this is the compute critical path, labeled as such."""
    out = []
    cfg = GPT2Config(
        vocab_size=50257, n_positions=4096, n_embd=768, n_layer=12, n_head=12
    )
    ids0 = jnp.asarray(rng.integers(0, 50000, size=(1, 8)), jnp.int32)
    params = GPT2Model(cfg).init(jax.random.PRNGKey(0), ids0)["params"]
    variants = {}
    shapes = (("full_2048", 2048), ("sp2_shard_1024", 1024))
    for name, Q in shapes:
        for R in (16, 64):
            variants[f"{name}/{R}"] = build_decode(
                "bfloat16", R, rng, params, Q=Q
            )
    best = interleaved_rounds(variants)
    _delete_tree((params, ids0))
    per_tok = {}
    for name, Q in shapes:
        t16, t64 = best[f"{name}/16"], best[f"{name}/64"]
        per_tok[name] = (t64 - t16) / 48
        rec = {
            "B": 8, "cache_positions": Q, "kv_cache_dtype": "bfloat16",
            "variant": name,
            "ms_per_decode_token": round(per_tok[name] * 1e3, 3),
            "sampler_call_s_R16": round(t16, 4),
            "sampler_call_s_R64": round(t64, 4),
        }
        out.append(rec)
        print(json.dumps({"measurement": "sp_decode", **rec}))
    summary = {
        "sp2_shard_over_full_ratio": round(
            per_tok["sp2_shard_1024"] / per_tok["full_2048"], 3
        ),
        "caveat": "compute critical path, single-chip; softmax-stats "
                  "psum + ICI excluded",
    }
    out.append(summary)
    print(json.dumps({"measurement": "sp_decode", **summary}))
    return out


# ------------------------------ ring sp=2 -------------------------------- #


def measure_ring_sp2(rng):
    """sp=2 ring critical-path compute at T=4096, single-chip.

    The lagging ring device (owner of q[2048:4096]) computes two 2048x2048
    blocks: one full (the other shard's keys) and one causal (its own).
    Measured as flash fwd+bwd vs the full-T single-device cost. Ideal
    compute ratio is 0.75 (6M of 8M score elements); the gap to ideal is
    blockwise overhead. ICI transfer/overlap is excluded, as labeled."""
    T = 4096
    half = T // 2

    def fwd_ring(args):
        q, k, v = args  # device 1 owns the second half of q
        q2 = q[:, half:]
        o_remote = attention_mod.dot_product_attention(
            q2, k[:, :half], v[:, :half], causal=False
        )
        o_local = attention_mod.dot_product_attention(
            q2, k[:, half:], v[:, half:], causal=True
        )
        return jnp.sum(o_remote.astype(jnp.float32)) + jnp.sum(
            o_local.astype(jnp.float32)
        )

    built = {
        "full": build_attn(T, "flash", rng, B=2),
        "ring": build_attn(T, "flash", rng, B=2, composite=fwd_ring),
    }
    variants = {m: t for m, (t, _) in built.items() if not isinstance(t, str)}
    if len(variants) < 2:  # an OOM here is a result, not a crash
        rec = {
            "T": T, "B": 2,
            "result": {m: t if isinstance(t, str) else "ok"
                       for m, (t, _) in built.items()},
        }
        print(json.dumps({"measurement": "ring_sp2", **rec}))
        return rec
    K = built["full"][1]
    best = interleaved_rounds(variants)
    full_ms = (best["full"] - FETCH_OVERHEAD_S) / K * 1e3
    ring_ms = (best["ring"] - FETCH_OVERHEAD_S) / K * 1e3
    rec = {
        "T": T, "B": 2,
        "full_ms_per_fwdbwd": round(full_ms, 3),
        "ring_sp2_critical_path_ms": round(ring_ms, 3),
        "measured_ratio": round(ring_ms / full_ms, 3),
        "ideal_compute_ratio": 0.75,
        "caveat": "compute only, single-chip; ICI transfer/overlap excluded",
    }
    print(json.dumps({"measurement": "ring_sp2", **rec}))
    return rec


def main():
    rng = np.random.default_rng(0)
    dev = jax.devices()[0]
    results = {"device_kind": dev.device_kind, "backend": jax.default_backend()}
    results["train_step"] = measure_train_steps(rng)
    results["attn_kernel"] = measure_attn_kernels(rng)
    results["decode"] = measure_decode(rng)
    results["sp_decode"] = measure_sp_decode(rng)
    results["ring_sp2"] = measure_ring_sp2(rng)
    _set_mode("flash")

    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "LONGCTX.json"), "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps({"written": "LONGCTX.json"}))


if __name__ == "__main__":
    main()
