"""Long-context hardware measurements on the real chip (VERDICT r2 #5).

Puts measured numbers behind the long-context claims that round 2 verified
only via compiled-HLO inspection:

1. ``train_step``  — full gpt2-small LM fwd+bwd+AdamW step at T=1024/2048/4096
   with the flash kernel engaged vs the XLA einsum path (token budget held
   constant at B*T = 8192).
2. ``attn_kernel`` — isolated causal attention fwd+bwd at the same shapes
   plus 8k, flash vs XLA.
3. ``decode``      — compiled sampler at a 2048-token prompt: prefill cost
   (flash vs XLA — prefill attends the full cache) and per-generated-token
   cost for bf16 vs int8 KV cache (R=16 vs R=64 differencing).
4. ``ring_sp2``    — the sp=2 ring-attention *per-device critical path*
   compute at T=4096 measured single-chip (the lagging device's two
   2048x2048 blocks), vs the full-T single-device cost. ICI overlap cost is
   NOT measurable on one chip; this grounds the compute half of the ring
   claim and is labeled as such.

Methodology (ROADMAP "measured, rejected" discipline): iterations chained
inside ONE jit via lax.scan over K distinct inputs, single fetch, best of 3
repeats — the tunnel's ~110 ms fetch and execution-cache traps make anything
shorter unreliable. OOM on the XLA path is caught and recorded as a result
("oom"), not an error: flash running where XLA cannot is the point.

Writes LONGCTX.json and prints one JSON line per measurement.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import trlx_tpu.ops.attention as attention_mod
from trlx_tpu.models.gpt2 import GPT2Config, GPT2Model, init_cache
from trlx_tpu.ops.sampling import GenerationConfig, make_sampler

FLASH_DEFAULT = attention_mod.FLASH_MIN_SEQ
XLA_ONLY = 1 << 30


def _set_mode(mode: str):
    attention_mod.FLASH_MIN_SEQ = FLASH_DEFAULT if mode == "flash" else XLA_ONLY


def _best_of(thunk, repeats=3):
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        thunk()
        best = min(best, time.perf_counter() - t0)
    return best


def _scan_timed(step_fn, carry, xs, iters):
    """Time ``iters`` chained executions of step_fn inside one jit."""

    def run(carry, xs):
        carry, out = jax.lax.scan(step_fn, carry, xs)
        return jax.tree_util.tree_map(
            lambda a: jnp.sum(a) if jnp.issubdtype(a.dtype, jnp.floating) else a,
            out,
        )

    fn = jax.jit(run)
    out = fn(carry, xs)  # compile + warmup
    jax.block_until_ready(out)
    sec = _best_of(lambda: jax.block_until_ready(fn(carry, xs)))
    return sec / iters


def measure_train_step(T, mode, rng):
    """One full LM fwd+bwd+AdamW step; B*T held at 8192 tokens."""
    _set_mode(mode)
    B = max(8192 // T, 1)
    cfg = GPT2Config(
        vocab_size=50257, n_positions=4096, n_embd=768, n_layer=12, n_head=12
    )
    model = GPT2Model(cfg)
    ids0 = jnp.asarray(rng.integers(0, 50000, size=(B, T)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids0)["params"]
    tx = optax.adamw(1e-4)
    opt_state = tx.init(params)

    def loss_fn(params, ids):
        out = model.apply({"params": params}, ids)
        logits = out["logits"][:, :-1]
        lp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(lp, ids[:, 1:, None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    def step(carry, ids):
        params, opt_state = carry
        loss, grads = jax.value_and_grad(loss_fn)(params, ids)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    K = 8
    batches = jnp.asarray(rng.integers(0, 50000, size=(K, B, T)), jnp.int32)
    try:
        sec = _scan_timed(step, (params, opt_state), batches, K)
    except Exception as e:  # XLA OOM at 4k without remat is a *result*
        if "RESOURCE_EXHAUSTED" in str(e) or "memory" in str(e).lower():
            return {"T": T, "B": B, "mode": mode, "result": "oom"}
        raise
    toks = B * T
    return {
        "T": T,
        "B": B,
        "mode": mode,
        "ms_per_step": round(sec * 1e3, 2),
        "tok_per_sec": round(toks / sec, 0),
    }


def measure_attn_kernel(T, mode, rng):
    """Isolated causal attention fwd+bwd, [B=4, T, H=12, D=64]."""
    _set_mode(mode)
    B, H, D = 4, 12, 64
    K = 4
    shape = (K, B, T, H, D)
    q = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)

    def fwd(args):
        q, k, v = args
        return jnp.sum(
            attention_mod.dot_product_attention(q, k, v, causal=True).astype(
                jnp.float32
            )
        )

    def step(carry, xs):
        val, grads = jax.value_and_grad(fwd)(xs)
        return carry, val + sum(
            jnp.sum(g.astype(jnp.float32)) for g in grads
        )

    try:
        sec = _scan_timed(step, 0.0, (q, k, v), K)
    except Exception as e:
        if "RESOURCE_EXHAUSTED" in str(e) or "memory" in str(e).lower():
            return {"T": T, "B": B, "mode": mode, "result": "oom"}
        raise
    return {"T": T, "B": B, "mode": mode, "ms_per_fwdbwd": round(sec * 1e3, 3)}


def measure_decode(kv_dtype, mode, rng):
    """Sampler at Q=2048 prompt: per-token decode cost via R differencing."""
    _set_mode(mode)
    B, Q = 8, 2048
    cfg = GPT2Config(
        vocab_size=50257,
        n_positions=4096,
        n_embd=768,
        n_layer=12,
        n_head=12,
        kv_cache_dtype=kv_dtype,
    )
    model = GPT2Model(cfg)
    ids0 = jnp.asarray(rng.integers(0, 50000, size=(1, 8)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids0)["params"]

    def apply_fn(params, input_ids, attention_mask=None, position_ids=None,
                 cache=None, cache_index=None):
        return model.apply(
            {"params": params}, input_ids, attention_mask=attention_mask,
            position_ids=position_ids, cache=cache, cache_index=cache_index,
        )

    prompt = jnp.asarray(rng.integers(0, 50000, size=(B, Q)), jnp.int32)
    mask = jnp.ones((B, Q), jnp.int32)
    times = {}
    for R in (16, 64):
        gen = GenerationConfig(
            max_new_tokens=R, min_new_tokens=R, do_sample=True, top_k=0,
            eos_token_id=50256, pad_token_id=50256,
        )
        sampler = jax.jit(
            make_sampler(apply_fn, lambda b, cap: init_cache(cfg, b, cap),
                         gen, Q, with_values=False)
        )
        rngs = [jax.random.PRNGKey(i) for i in range(3)]
        out = sampler(params, prompt, mask, rngs[0])
        jax.block_until_ready(out.tokens)
        times[R] = _best_of(
            lambda: jax.block_until_ready(
                sampler(params, prompt, mask, rngs[1]).tokens
            )
        )
    per_tok_ms = (times[64] - times[16]) / 48 * 1e3
    prefill_ms = (times[16] - 16 * (times[64] - times[16]) / 48) * 1e3
    return {
        "B": B,
        "prompt_len": Q,
        "kv_cache_dtype": kv_dtype,
        "mode": mode,
        "ms_per_decode_token": round(per_tok_ms, 3),
        "prefill_ms": round(max(prefill_ms, 0.0), 2),
    }


def measure_ring_sp2(rng):
    """sp=2 ring critical-path compute at T=4096, single-chip.

    The lagging ring device (owner of q[2048:4096]) computes two
    2048x2048 blocks: one full (vs the other shard's keys) and one causal
    (its own). Measured as flash fwd+bwd; compared against the full-T
    single-device flash cost. Ideal compute ratio is 0.75 (6M of 8M score
    elements); the gap to ideal is blockwise overhead. ICI transfer/overlap
    is not measurable on one chip and is excluded, as labeled.
    """
    _set_mode("flash")
    B, H, D, T = 2, 12, 64, 4096
    half = T // 2
    K = 4
    full = tuple(
        jnp.asarray(rng.standard_normal((K, B, T, H, D)), jnp.bfloat16)
        for _ in range(3)
    )

    def fwd_full(args):
        q, k, v = args
        return jnp.sum(
            attention_mod.dot_product_attention(q, k, v, causal=True).astype(
                jnp.float32
            )
        )

    def step_full(c, xs):
        val, grads = jax.value_and_grad(fwd_full)(xs)
        return c, val + sum(jnp.sum(g.astype(jnp.float32)) for g in grads)

    sec_full = _scan_timed(step_full, 0.0, full, K)

    def fwd_ring(args):
        q, k, v = args  # [B, T, H, D]; device 1 owns the second half of q
        q2 = q[:, half:]
        o_remote = attention_mod.dot_product_attention(
            q2, k[:, :half], v[:, :half], causal=False
        )
        o_local = attention_mod.dot_product_attention(
            q2, k[:, half:], v[:, half:], causal=True
        )
        # combine cost (online-softmax lse merge) is negligible vs the
        # blocks; summing both outputs keeps the timing honest about reads
        return jnp.sum(o_remote.astype(jnp.float32)) + jnp.sum(
            o_local.astype(jnp.float32)
        )

    def step_ring(c, xs):
        val, grads = jax.value_and_grad(fwd_ring)(xs)
        return c, val + sum(jnp.sum(g.astype(jnp.float32)) for g in grads)

    sec_ring = _scan_timed(step_ring, 0.0, full, K)
    return {
        "T": T,
        "B": B,
        "full_ms_per_fwdbwd": round(sec_full * 1e3, 3),
        "ring_sp2_critical_path_ms": round(sec_ring * 1e3, 3),
        "measured_ratio": round(sec_ring / sec_full, 3),
        "ideal_compute_ratio": 0.75,
        "caveat": "compute only, single-chip; ICI transfer/overlap excluded",
    }


def main():
    rng = np.random.default_rng(0)
    dev = jax.devices()[0]
    results = {
        "device_kind": dev.device_kind,
        "backend": jax.default_backend(),
        "train_step": [],
        "attn_kernel": [],
        "decode": [],
    }
    for T in (1024, 2048, 4096):
        for mode in ("flash", "xla"):
            r = measure_train_step(T, mode, rng)
            results["train_step"].append(r)
            print(json.dumps({"measurement": "train_step", **r}))
    for T in (1024, 2048, 4096, 8192):
        for mode in ("flash", "xla"):
            r = measure_attn_kernel(T, mode, rng)
            results["attn_kernel"].append(r)
            print(json.dumps({"measurement": "attn_kernel", **r}))
    for kv_dtype in ("bfloat16", "int8"):
        for mode in ("flash", "xla"):
            r = measure_decode(kv_dtype, mode, rng)
            results["decode"].append(r)
            print(json.dumps({"measurement": "decode", **r}))
    r = measure_ring_sp2(rng)
    results["ring_sp2"] = r
    print(json.dumps({"measurement": "ring_sp2", **r}))
    _set_mode("flash")

    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "LONGCTX.json"), "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps({"written": "LONGCTX.json"}))


if __name__ == "__main__":
    main()
