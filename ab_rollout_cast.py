"""A/B: rollout-phase weight cast (bf16 copy) vs f32 masters, real TPU.

Measures `train.rollout_param_cast` on the bench workload shape (gpt2-small,
int8 KV cache, B=128, Q=64, R=48): the sampler re-reads every parameter once
per generated token, so f32 masters cost 2x the weight HBM traffic of the
bf16 compute-dtype copy the cast serves. Outputs are bit-identical
(`tests/test_rollout_cast.py`); this script settles whether the traffic
saving is wall-clock real.

Methodology per `ab_int8_kv.py`: per measurement, queue K sampler dispatches
on DISTINCT inputs (execution caching makes repeated identical calls free),
force with ONE summed fetch (~110 ms flat), and interleave variants across
rounds (wall-clock swings ±20% with machine load).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("WANDB_DISABLED", "1")

import numpy as np


def build_trainer(cast: bool):
    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.utils.loading import get_trainer

    config = TRLConfig.from_dict(
        {
            "model": {
                "model_type": "gpt2",
                "model_arch": {
                    "vocab_size": 50257, "n_positions": 1024, "n_embd": 768,
                    "n_layer": 12, "n_head": 12, "kv_cache_dtype": "int8",
                },
            },
            "train": {
                "seq_length": 64, "batch_size": 16, "epochs": 1,
                "total_steps": 10000, "eval_interval": 100000,
                "checkpoint_interval": 1000000,
                "mesh": {"dp": -1, "fsdp": 1, "tp": 1}, "dtype": "bfloat16",
                "rollout_param_cast": cast,
            },
            "method": {
                "name": "PPOConfig", "num_rollouts": 128, "chunk_size": 128,
                "ppo_epochs": 4,
                "gen_kwargs": {
                    "max_new_tokens": 48, "min_new_tokens": 48, "top_k": 0,
                    "do_sample": True, "eos_token_id": 50256,
                    "pad_token_id": 50256,
                },
            },
        }
    )
    return get_trainer(config.train.trainer)(
        config, reward_fn=lambda **kw: [0.0]
    )


def main():
    import jax
    import jax.numpy as jnp

    B, Q, K = 128, 64, 10
    rng = np.random.default_rng(0)

    def fresh_batches(n):
        return [
            (
                jnp.asarray(rng.integers(100, 40000, (B, Q)), jnp.int32),
                jnp.ones((B, Q), jnp.int32),
            )
            for _ in range(n)
        ]

    trainers = {"f32": build_trainer(False), "cast": build_trainer(True)}

    def measure(trainer, batches):
        t0 = time.time()
        acc = jnp.zeros((), jnp.int32)
        for ids, mask in batches:
            out = trainer.sample(ids, mask)
            acc = acc + out.tokens.sum()
        _ = int(acc)  # single forcing fetch
        return time.time() - t0

    def measure_ref(trainer, batches):
        """score_ref also runs on the cast copy — time it too."""
        t0 = time.time()
        acc = jnp.zeros((), jnp.float32)
        for ids, mask in batches:
            r_ids = jnp.asarray(
                rng.integers(100, 40000, (B, 48)), jnp.int32
            )
            r_mask = jnp.ones((B, 48), jnp.int32)
            lp = trainer.score_ref(ids, mask, r_ids, r_mask)
            acc = acc + lp.sum()
        _ = float(acc)
        return time.time() - t0

    for t in trainers.values():  # warm the compiled paths
        measure(t, fresh_batches(1))
        measure_ref(t, fresh_batches(1))

    rounds = {"f32": [], "cast": []}
    ref_rounds = {"f32": [], "cast": []}
    for r in range(6):
        for name in ("f32", "cast") if r % 2 == 0 else ("cast", "f32"):
            rounds[name].append(measure(trainers[name], fresh_batches(K)))
            ref_rounds[name].append(
                measure_ref(trainers[name], fresh_batches(K))
            )
    for label, data in (("sampler", rounds), ("score_ref", ref_rounds)):
        for name, ts in data.items():
            per_call = [(t - 0.11) / K for t in ts]
            print(
                f"{label}/{name}: per-call mean {np.mean(per_call)*1e3:.1f} ms  "
                f"median {np.median(per_call)*1e3:.1f} ms  "
                f"all {[round(x*1e3, 1) for x in per_call]}"
            )
    for label, data in (("sampler", rounds), ("score_ref", ref_rounds)):
        speedup = np.median(data["f32"]) / np.median(data["cast"])
        print(f"{label}: cast speedup over f32 masters: {speedup:.3f}x")


if __name__ == "__main__":
    main()
