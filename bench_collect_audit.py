"""One-off audit: where does the collect phase's time actually go?

Breaks one bench-shape PPO phase (B=128, Q=64, R=48, gpt2-small bf16,
int8 KV cache) into serialized components, each forced with a real
device->host value fetch (block_until_ready does not force execution on
the tunneled axon backend). Methodology per bench_longctx.py: fresh rng
per timed call (the sampler splits its key per invocation, so inputs are
always distinct), compile warmup first, best-of-N over interleaved rounds.

Prints a JSON dict of milliseconds.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("WANDB_DISABLED", "1")

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.utils.loading import get_orchestrator, get_pipeline, get_trainer


def bench_config():
    return TRLConfig.from_dict(
        {
            "model": {
                "model_type": "gpt2",
                "model_arch": {
                    "vocab_size": 50257,
                    "n_positions": 1024,
                    "n_embd": 768,
                    "n_layer": 12,
                    "n_head": 12,
                    "kv_cache_dtype": "int8",
                },
            },
            "train": {
                "seq_length": 64,
                "batch_size": 16,
                "epochs": 3,
                "total_steps": 10000,
                "eval_interval": 100000,
                "checkpoint_interval": 1000000,
                "mesh": {"dp": -1, "fsdp": 1, "tp": 1},
                "dtype": "bfloat16",
            },
            "method": {
                "name": "PPOConfig",
                "num_rollouts": 128,
                "chunk_size": 128,
                "ppo_epochs": 4,
                "init_kl_coef": 0.05,
                "scale_reward": "running",
                "gen_kwargs": {
                    "max_new_tokens": 48,
                    "min_new_tokens": 48,
                    "top_k": 0,
                    "do_sample": True,
                    "eos_token_id": 50256,
                    "pad_token_id": 50256,
                },
            },
        }
    )


def force(x):
    """Real value fetch — the only thing that forces execution here."""
    return float(jnp.ravel(x)[0])


def bench_reward_fn(samples, queries, response_gt=None):
    """The bench workload's cheap host reward (one definition for the
    audit + every ab_* script — a drifted copy would silently measure a
    different workload)."""
    return [len(set(s)) / max(len(s), 1) for s in samples]


def make_bench_workload(chunk_size=None):
    """(trainer, pipeline, orchestrator) at the bench shape — shared setup
    for the A/B scripts."""
    from trlx_tpu.utils.loading import (
        get_orchestrator, get_pipeline, get_trainer,
    )

    config = bench_config()
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(100, 40000, size=rng.integers(4, 33)))
               for _ in range(512)]
    trainer = get_trainer(config.train.trainer)(
        config, reward_fn=bench_reward_fn
    )
    pipeline = get_pipeline(config.train.pipeline)(
        prompts, config.train.seq_length
    )
    orch = get_orchestrator(config.train.orchestrator)(
        trainer, pipeline, reward_fn=bench_reward_fn,
        chunk_size=chunk_size or config.method.chunk_size,
    )
    return config, trainer, pipeline, orch


def main():
    config = bench_config()
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(100, 40000, size=rng.integers(4, 33)))
               for _ in range(512)]

    def reward_fn(samples, queries, response_gt=None):
        return [len(set(s)) / max(len(s), 1) for s in samples]

    trainer = get_trainer(config.train.trainer)(config, reward_fn=reward_fn)
    pipeline = get_pipeline(config.train.pipeline)(
        prompts, config.train.seq_length
    )
    orch = get_orchestrator(config.train.orchestrator)(
        trainer, pipeline, reward_fn=reward_fn,
        chunk_size=config.method.chunk_size,
    )

    # ---- warmup: compile sampler, ref, rewards, train phase ----
    for _ in range(2):
        trainer.buffer.clear_history()
        orch.make_experience(config.method.num_rollouts, 0)
        trainer.train_on_buffer()
        force(jax.tree_util.tree_leaves(trainer.state.params)[0])

    out = {}

    # ---- tunnel round-trip: fetch of an already-materialized scalar ----
    z = jnp.zeros(())
    force(z)
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        force(z)
        ts.append((time.perf_counter() - t0) * 1000)
    out["roundtrip_ms"] = round(min(ts), 1)

    batch, meta = next(orch._loader)

    def timed(fn, n=3):
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            best = min(best, (time.perf_counter() - t0) * 1000)
        return round(best, 1)

    # ---- sampler alone (exec + roundtrip) ----
    def run_sample():
        so = trainer.sample(batch.input_ids, batch.attention_mask)
        force(so.tokens)
        return so

    out["sample_ms"] = timed(run_sample)

    # ---- sampler + ref forward chained ----
    def run_sample_ref():
        so = trainer.sample(batch.input_ids, batch.attention_mask)
        ref = trainer.score_ref(
            batch.input_ids, batch.attention_mask, so.tokens, so.response_mask
        )
        force(ref)

    out["sample_ref_ms"] = timed(run_sample_ref)

    # ---- ref alone (on fixed tokens; approx = sample_ref - sample) ----
    so = trainer.sample(batch.input_ids, batch.attention_mask)
    jax.device_get(so.tokens)

    # ---- host tail: decode + reward + numpy scaling (no device work:
    #      decode_responses' device_get is a no-op on numpy arrays) ----
    toks, mask = jax.device_get((so.tokens, so.response_mask))

    def host_tail():
        texts = trainer.decode_responses(toks, mask)
        scores = np.asarray(reward_fn(texts, None), dtype=np.float32)
        return scores

    out["host_decode_reward_ms"] = timed(host_tail)

    # ---- full make_experience (forced by its own internal fetch +
    #      forcing the pushed rewards at the end) ----
    def run_collect():
        trainer.buffer.clear_history()
        orch.make_experience(config.method.num_rollouts, 0)
        force(trainer.buffer._chunks[-1].rewards)

    out["collect_ms"] = timed(run_collect)

    # ---- train phase alone (buffer already filled by last collect) ----
    def run_train():
        trainer.train_on_buffer()
        force(jax.tree_util.tree_leaves(trainer.state.params)[0])

    out["train_ms"] = timed(run_train)

    # ---- full phase, as bench.py sequences it ----
    def run_phase():
        trainer.buffer.clear_history()
        orch.make_experience(config.method.num_rollouts, 0)
        trainer.train_on_buffer()
        force(jax.tree_util.tree_leaves(trainer.state.params)[0])

    out["phase_ms"] = timed(run_phase)

    out["device_kind"] = jax.devices()[0].device_kind
    print(json.dumps(out))


if __name__ == "__main__":
    main()
