"""Standalone repro of the jax 0.4.x two-process startup-barrier abort.

``tests/test_multiprocess.py::test_two_process_sharded_ppo_step`` is
quarantined (``xfail(run=False)``) because
``multihost_utils.sync_global_devices`` aborts inside
``broadcast_one_to_all`` at the startup barrier for a two-process CPU
rendezvous in this container — library-level, before any repo logic
runs.  That quarantine is the first blocker of ROADMAP direction 1
(real multi-controller execution); until it lifts, the lockstep
auditor (``python -m trlx_tpu.analysis --lockstep``) is the stand-in
gate for N-host dispatch agreement.

This probe isolates the minimal trigger: two OS processes join one JAX
runtime via ``jax.distributed.initialize`` (coordinator on a localhost
port) and immediately call ``sync_global_devices("startup")`` followed
by a ``broadcast_one_to_all`` round-trip — the exact call pair
``parallel/distributed.py::barrier``/``broadcast_host_value`` make, with
no trainer, mesh, or model anywhere in the process.

Run::

    python tools/multiprocess_probe.py            # spawn 2 ranks, diagnose
    python tools/multiprocess_probe.py --procs 2  # explicit rank count

Expected output on this container's jaxlib (the bug present)::

    REPRODUCED: sync_global_devices aborted at the startup barrier
    ... (first error lines from the failing rank) ...

After a jaxlib bump that fixes the rendezvous the probe prints
``FIXED UPSTREAM`` — at which point the ``test_multiprocess.py``
quarantine, the ROADMAP entry, and this file can be retired, and
direction 1 unblocks.  Exit status: 0 for both the REPRODUCED and
FIXED UPSTREAM verdicts (the probe is informational, like
``tools/pp_miscompile_repro.py``); 1 only for an unexpected failure
shape (e.g. ranks hang past the timeout or die before the barrier),
which means the quarantine reason needs re-diagnosis, not retirement.
"""

import argparse
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_TIMEOUT = 300
_SENTINEL = "probe rank {rank}: barrier + broadcast ok"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env() -> dict:
    env = dict(os.environ)
    # one virtual CPU device per rank — the barrier needs no mesh; scrub
    # any single-process device-count flag this process inherited
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append("--xla_force_host_platform_device_count=1")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def worker(coordinator: str, num_processes: int, rank: int) -> None:
    """One rank: initialize, hit the startup barrier, broadcast once."""
    import jax

    # the env's sitecustomize may force-select a TPU platform at
    # interpreter startup (outranking JAX_PLATFORMS) — same recipe as
    # parallel/_mp_smoke.py
    jax.config.update("jax_platforms", "cpu")

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=rank,
    )
    assert jax.process_count() == num_processes, jax.process_count()

    from jax.experimental import multihost_utils

    # the abort site: barrier() delegates here when process_count > 1
    multihost_utils.sync_global_devices("startup")
    # the other half of the pair distributed.py leans on
    value = multihost_utils.broadcast_one_to_all(
        1234 if rank == 0 else -1
    )
    assert int(value) == 1234, value
    print(_SENTINEL.format(rank=rank), flush=True)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--procs", type=int, default=2)
    parser.add_argument(
        "--worker",
        nargs=3,
        metavar=("COORDINATOR", "NPROCS", "RANK"),
        help=argparse.SUPPRESS,
    )
    args = parser.parse_args()

    if args.worker:
        coordinator, nprocs, rank = args.worker
        worker(coordinator, int(nprocs), int(rank))
        return 0

    coordinator = f"127.0.0.1:{_free_port()}"
    env = _worker_env()
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                os.path.abspath(__file__),
                "--worker",
                coordinator,
                str(args.procs),
                str(rank),
            ],
            env=env,
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in range(args.procs)
    ]
    outs = []
    hung = False
    try:
        for p in procs:
            out, _ = p.communicate(timeout=_TIMEOUT)
            outs.append(out)
    except subprocess.TimeoutExpired:
        hung = True
        for p in procs:
            p.kill()
            out, _ = p.communicate()
            outs.append(out)

    ok = not hung and all(p.returncode == 0 for p in procs)
    synced = all(
        _SENTINEL.format(rank=r) in out for r, out in enumerate(outs)
    )
    if ok and synced:
        print("FIXED UPSTREAM: sync_global_devices + broadcast_one_to_all")
        print(
            "completed across %d processes — retire the "
            "test_multiprocess.py quarantine, the ROADMAP entry, and "
            "this probe; direction 1 unblocks." % args.procs
        )
        return 0

    # classify the failure: the known bug aborts at/inside the barrier
    # AFTER distributed.initialize succeeded (ranks print nothing)
    joined = "\n".join(outs)
    barrier_abort = not hung and not synced
    if hung:
        print(
            "UNEXPECTED: ranks hung for %ds instead of aborting — "
            "re-diagnose before trusting the quarantine reason."
            % _TIMEOUT
        )
    elif barrier_abort:
        print("REPRODUCED: sync_global_devices aborted at the startup")
        print(
            "barrier (library-level, before any repo logic) — the "
            "test_multiprocess.py quarantine stands."
        )
    for rank, out in enumerate(outs):
        head = [ln for ln in out.splitlines() if ln.strip()][:8]
        if head:
            print(f"--- rank {rank} (rc={procs[rank].returncode}) ---")
            print("\n".join(head))
    if barrier_abort:
        return 0
    print(joined[-2000:] if len(joined) > 2000 else "", end="")
    return 1


if __name__ == "__main__":
    sys.exit(main())
