"""Standalone repro of the XLA SPMD pp stage-stacking miscompile.

jaxlib 0.4.36's SPMD partitioner miscompiles the jitted pipeline-parallel
model-stage program when the per-stage parameter stack is built with
``jnp.stack`` (lowered to ``concatenate``) and fed to a ``shard_map``
with a ``P("pp")`` in_spec on any mesh with a second size>1 axis (dp,
fsdp, or tp — all confirmed): the stages read wrong slices of the
stacked operand, producing O(1)-wrong activations on ~100% of elements
(max diff ~3 at the tiny-GPT-2 shape below). Eager execution of the
*identical* program is exact (~1e-6), and the generic pipeline schedule
primitives pass their own jit parity tests — the trigger needs the real
transformer stage body. Same compiler-bug family as the sharded-concat
replica-sum documented at ``trlx_tpu/data/ppo_types.py::concat_rollouts``
(PR 2).

Workaround shipped in-tree: ``trlx_tpu/parallel/pipeline.py::spmd_stack``
builds the [S]-leading stacks from ``dynamic_update_slice`` writes into a
zeros buffer instead of ``concatenate``; every stage-stacking path
(``stack_stage_params``, ``_stack_stages``, the interleaved variant) goes
through it, which flips the quarantined ``test_pp_integration.py`` train
parity tests from fail to pass.

Run::

    python tools/pp_miscompile_repro.py            # A/B both stackings
    python tools/pp_miscompile_repro.py --broken   # only the jnp.stack lowering

Expected output on jaxlib 0.4.36 (8 virtual CPU devices)::

    spmd_stack (workaround)  fwd max|diff| 0.000e+00  grad max|diff| 2.4e-07   OK
    jnp.stack  (broken)      fwd max|diff| 2.987e+00  grad max|diff| 1.0e+00   MISCOMPILED

Exit status: 0 when the workaround variant is exact (the repro is
*informational* for the broken variant — a newer jaxlib that fixes the
bug prints ``FIXED UPSTREAM`` and this file + the ROADMAP entry can be
retired); 1 if the workaround itself diverges.

Minimization notes (for the upstream report): the trigger is NOT
reproducible with a plain matmul stage — a ``shard_map(P("pp"), ...)``
over a ``jnp.stack`` of host or committed-fsdp-sharded weights, with or
without the full ``fori_loop`` + ``ppermute`` + masked-write pipeline
schedule around it, compiles correctly on this jaxlib. The smallest
known trigger is the real flax transformer Block as the stage body
(attention + MLP under ``remat``-free apply), i.e. exactly what
``pp_response_forward`` runs; the A/B below therefore drives the repo's
own stage path at the smallest shape that shows the bug. Decode is hit
separately: the cached-decode path still miscompiles even with
``spmd_stack`` (wrong sampled tokens on the pp mesh — see the quarantined
decode tests and the ROADMAP entry), so the sampler keeps its own
``dynamic_update_slice`` concat workarounds (``ops/sampling.py``) and the
decode tests stay quarantined until a jaxlib bump fixes both.
"""

import argparse
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("WANDB_DISABLED", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ARCH = {
    "vocab_size": 16, "n_positions": 16, "n_embd": 32,
    "n_layer": 4, "n_head": 2,
}
MESH = {"dp": -1, "fsdp": 1, "tp": 1, "pp": 2}


def _build_trainer():
    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.utils.loading import get_trainer

    config = TRLConfig.from_dict({
        "model": {"model_type": "gpt2", "model_arch": dict(ARCH)},
        "train": {
            "seq_length": 4, "batch_size": 16, "epochs": 2,
            "total_steps": 8, "eval_interval": 1000,
            "checkpoint_interval": 100000,
            "lr_init": 1e-3, "lr_target": 1e-3,
            "mesh": dict(MESH), "dtype": "float32", "seed": 7,
        },
        "method": {
            "name": "PPOConfig", "num_rollouts": 32, "chunk_size": 32,
            "ppo_epochs": 2, "init_kl_coef": 0.001, "scale_reward": None,
            "gen_kwargs": {
                "max_new_tokens": 6, "min_new_tokens": 6, "top_k": 0,
                "do_sample": True, "eos_token_id": 14, "pad_token_id": 15,
            },
        },
    })
    trainer = get_trainer("PPOTrainer")(config, reward_fn=lambda **kw: [0.0])
    return config, trainer


def run_variant(use_jnp_stack: bool):
    """Forward+grad jit parity of pp_response_forward vs the plain
    backbone, with the stage stacking swapped to the requested lowering.
    The swap MUST precede trainer construction — the stacking runs when
    the pp runner first materializes stage params."""
    import jax
    import jax.flatten_util
    import jax.numpy as jnp
    import numpy as np

    import trlx_tpu.models.pp_runner as runner
    import trlx_tpu.parallel.pipeline as plib

    orig = plib.spmd_stack
    if use_jnp_stack:
        broken = lambda *xs: jnp.stack(xs, axis=0)  # noqa: E731
        plib.spmd_stack = broken
        runner.spmd_stack = broken
    try:
        config, trainer = _build_trainer()
        params = jax.device_get(trainer.state.params)
        rng = np.random.default_rng(0)
        B, Q, R = 16, 4, 6
        full_ids = jnp.asarray(rng.integers(1, 13, (B, Q + R)), jnp.int32)
        full_mask = jnp.ones((B, Q + R), jnp.int32)

        from trlx_tpu.models.pp_runner import pp_response_forward

        def pp_path(p):
            return pp_response_forward(
                trainer.model_config, p, full_ids, full_mask, Q,
                trainer.mesh, config.train.pp_microbatches,
            )

        def plain_path(p):
            return trainer.model.apply(
                {"params": p}, full_ids, full_mask, Q,
                method=trainer.model.response_forward,
            )

        pl_logits, _ = jax.jit(plain_path)(params)
        pp_logits, _ = jax.jit(pp_path)(params)
        fwd = float(jnp.max(jnp.abs(pp_logits - pl_logits)))

        def loss(path):
            def f(p):
                logits, values = path(p)
                return jnp.mean(logits**2) + jnp.mean(values**2)
            return f

        g_pp = jax.jit(jax.grad(loss(pp_path)))(params)
        g_pl = jax.jit(jax.grad(loss(plain_path)))(params)
        f_pp, _ = jax.flatten_util.ravel_pytree(jax.device_get(g_pp))
        f_pl, _ = jax.flatten_util.ravel_pytree(jax.device_get(g_pl))
        grad = float(np.max(np.abs(np.asarray(f_pp) - np.asarray(f_pl))))
        return fwd, grad
    finally:
        plib.spmd_stack = orig
        runner.spmd_stack = orig


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--broken", action="store_true",
        help="run only the jnp.stack lowering (the miscompile)",
    )
    args = parser.parse_args()

    import jax

    print(f"jax {jax.__version__}, {len(jax.devices())} devices, mesh {MESH}")
    tol = 1e-4
    status = 0
    variants = [(True, "jnp.stack  (broken)")] if args.broken else [
        (False, "spmd_stack (workaround)"),
        (True, "jnp.stack  (broken)"),
    ]
    for use_stack, label in variants:
        fwd, grad = run_variant(use_stack)
        bad = fwd > tol or grad > 1e-3
        if use_stack:
            verdict = "MISCOMPILED (bug still present)" if bad else (
                "FIXED UPSTREAM — retire this repro + the ROADMAP entry"
            )
        else:
            verdict = "OK" if not bad else "WORKAROUND BROKEN"
            status |= int(bad)
        print(
            f"{label}  fwd max|diff| {fwd:.3e}  grad max|diff| {grad:.1e}"
            f"   {verdict}"
        )
    return status


if __name__ == "__main__":
    sys.exit(main())
