"""A/B: asynchronous actor–learner PPO vs the serial same-plan phase.

One full PPO phase per timed region, both variants on the continuous
rollout engine (the actors) and the SAME
:class:`~trlx_tpu.pipeline.ppo_buffer.StreamPlan`:

- **async**: ``train.async_rl: {enabled, staleness_window: 1}`` — the
  learner consumes epoch-1 minibatches as their rows land and pushes
  refreshed weights to the engine MID-generation under the bounded-
  staleness window (docs/async_pipeline.md);
- **serial**: the identical plan, every update dispatched after
  collection completes (``overlap=False`` — the pre-async phase
  structure and the ``staleness_window: 0`` degenerate mode's
  execution order).

Methodology per ab_phase_overlap.py: compile warmup, variants
interleaved across rounds, best-of-N, one forcing fetch per timed
region. Before timing, the script runs the async self-check
(`trlx_tpu.analysis.async_smoke`): the ``staleness_window=0`` phase
must be BITWISE-identical to the serial same-plan phase, and a planted
dead actor (``engine.admit`` chaos) must surface an ``actor-dead``
health event and recover via the resilience supervisor with no hang —
an A/B whose two arms could diverge semantically, or whose failure
path hangs, measures nothing.

Prints one JSON line and RECORDS it into ``AB_ASYNC_RL.json`` (repo
root, `utils/ab_record.py`): the latest dated record per (metric,
device_kind) — the first hardware run lands the TPU throughput delta
in a committed artifact automatically.

Measured delta: CPU runs verify parity + plumbing only — host and
device contend for one core, so the learner work the async schedule
hides inside decode is not actually hidden on CPU (same story as
ab_phase_overlap.py, whose CPU record is 0.98x). Measured on this
image (1-core CPU, tiny shape, 2026-08-04): async 1023.8 ms vs serial
1027.5 ms per phase (1.00x — the expected wash) with 4/4 epoch-1
updates consumed during collection, 3 in-flight weight pushes,
staleness p50 1.0 bounded by the window of 1, and both smoke scenarios
green. The headline number is the first hardware round: collect MFU
0.157 means the learner idles most of every serial phase — the async
schedule's upper bound is hiding all of epoch-1 plus the drain inside
that window. See AB_ASYNC_RL.json for the latest dated record per
(metric, device_kind).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("WANDB_DISABLED", "1")

import jax
import numpy as np

from bench_collect_audit import (
    bench_config, bench_reward_fn as reward_fn, force,
)


def make_workload(async_rl):
    """Bench-shape continuous-engine workload; chunk 16 << rollouts 128
    gives the async learner real landing boundaries. CPU shrinks the
    model/phase (the CPU tier proves parity + plumbing, not the
    delta)."""
    from trlx_tpu.utils.loading import (
        get_orchestrator, get_pipeline, get_trainer,
    )

    config = bench_config()
    config.train.rollout = {"engine": "continuous"}
    if async_rl:
        config.train.async_rl = dict(async_rl)
    if jax.default_backend() == "cpu":
        config.update(
            model={"model_arch": {
                "vocab_size": 512, "n_positions": 128, "n_embd": 64,
                "n_layer": 2, "n_head": 2, "kv_cache_dtype": "bfloat16",
            }},
            method={
                "num_rollouts": 64,
                "gen_kwargs": dict(
                    config.method.gen_kwargs,
                    max_new_tokens=8, min_new_tokens=8,
                    eos_token_id=510, pad_token_id=511,
                ),
            },
        )
        config.train.rollout = {
            "engine": "continuous", "slots": 16, "admit_width": 16,
            "harvest_width": 16,
        }
    rng = np.random.default_rng(0)
    vocab = config.model.model_arch["vocab_size"]
    prompts = [
        list(rng.integers(1, vocab - 8, size=rng.integers(4, 33)))
        for _ in range(512)
    ]
    trainer = get_trainer(config.train.trainer)(
        config, reward_fn=reward_fn
    )
    pipeline = get_pipeline(config.train.pipeline)(
        prompts, config.train.seq_length
    )
    orch = get_orchestrator(config.train.orchestrator)(
        trainer, pipeline, reward_fn=reward_fn, chunk_size=16
    )
    return config, trainer, pipeline, orch


def main():
    # self-check first: bitwise W=0 parity + dead-actor recovery (an
    # A/B over semantically-divergent arms measures nothing)
    from trlx_tpu.analysis.async_smoke import run_async_smoke

    smoke = run_async_smoke()
    smoke_flags = {
        "parity_w0_bitwise": bool(
            smoke["scenarios"]["staleness0_parity"].get("passed")
        ),
        "dead_actor_recovered": bool(
            smoke["scenarios"]["dead_actor_recovery"].get("passed")
        ),
    }
    if not smoke["passed"]:
        print(json.dumps({"error": "async smoke failed", **smoke_flags,
                          "scenarios": smoke["scenarios"]}, default=str))
        return 1

    config, trainer, pipeline, orch = make_workload(
        {"enabled": True, "staleness_window": 1}
    )
    num_rollouts = config.method.num_rollouts
    seed_counter = [0]

    def run_phase(overlap):
        seed_counter[0] += 1
        trainer.buffer.clear_history()
        # overlap=None → the async schedule (guard + in-flight pushes);
        # overlap=False → the serial same-plan baseline (the explicit
        # escape begin_streamed_phase honors even under async config)
        trainer.begin_streamed_phase(seed=seed_counter[0], overlap=overlap)
        orch.make_experience(num_rollouts, 0)
        trainer.finish_streamed_phase()
        force(jax.tree_util.tree_leaves(trainer.state.params)[0])

    variants = {
        "async": lambda: run_phase(None),
        "serial": lambda: run_phase(False),
    }
    for fn in variants.values():  # compile warmup
        fn()
    for fn in variants.values():  # absorb donated-buffer relayout retrace
        fn()

    best = {k: float("inf") for k in variants}
    async_stats = {}
    order = list(variants)
    for rnd in range(4):
        for k in order if rnd % 2 == 0 else reversed(order):
            t0 = time.perf_counter()
            variants[k]()
            best[k] = min(best[k], (time.perf_counter() - t0) * 1000)
            if k == "async":
                async_stats = {
                    key: round(v, 3)
                    for key, v in trainer._last_overlap_stats.items()
                    if key.startswith("async/")
                    or key == "exp/overlap_streamed_updates"
                }

    shape = (
        "ppo_async_phase_ms_B128_Q64_R48_gpt2s_chunk16"
        if jax.default_backend() != "cpu"
        else "ppo_async_phase_ms_cpu_tiny_chunk16"
    )
    record = {
        "metric": shape,
        **{f"{k}_ms": round(v, 1) for k, v in best.items()},
        "async_speedup_vs_serial": round(best["serial"] / best["async"], 3),
        **async_stats,
        **smoke_flags,
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(record))
    from trlx_tpu.utils.ab_record import record_latest

    record_latest(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "AB_ASYNC_RL.json"),
        record,
    )
    # run-ledger history next to the latest-per-key artifact, so any
    # two async A/B rounds diff via `telemetry --compare`
    from trlx_tpu.telemetry.run_ledger import append_ab_manifest

    append_ab_manifest("ab_async_rl", record)
    return 0


if __name__ == "__main__":
    sys.exit(main())
