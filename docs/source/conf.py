"""Sphinx configuration for trlx_tpu docs."""

import os
import sys

sys.path.insert(0, os.path.abspath("../.."))

project = "trlx_tpu"
author = "trlx_tpu contributors"
release = "0.1.0"

extensions = [
    "sphinx.ext.autodoc",
    "sphinx.ext.napoleon",
    "sphinx.ext.viewcode",
]

autodoc_mock_imports = ["jax", "flax", "optax", "orbax", "transformers", "torch"]
html_theme = "sphinx_rtd_theme"
exclude_patterns = []
