"""A/B: train phase with top-2 layer freezing vs full training.

(r5 correction of this header's claim: the reference as SHIPPED trains
all 12 layers — its PPO freezing block is commented out,
`accelerate_base_model.py:55-69`; `test_config.yml:5`'s
``num_layers_unfrozen: 2`` only sizes the hydra KL-ref branch. Full
training is therefore the FAITHFUL workload and the bench headline;
freezing is the work-avoidance capability this file measures the delta
of.) Round 4 made freezing real work-avoidance: stop_gradient on frozen
leaves (XLA dead-code-eliminates the backward below the branch point)
and optax.masked moments (frozen params carry no optimizer state or
Adam traffic).

This measures that delta in ONE session with the interleaved methodology
(bench_longctx.py / MEMORY.md): one trainer, the freezing swapped in
place (mask + optimizer + re-jitted train phase — fresh closures, so no
trace-cache aliasing), globally-unique shuffle seeds per timed call,
interleaved order across rounds, best-of-N, forcing value fetch with the
measured tunnel round-trip subtracted.

Prints one JSON line with per-variant best ms (round-trip excluded) and
the speedup.
"""

import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("WANDB_DISABLED", "1")

import jax
import jax.numpy as jnp

from bench_collect_audit import force, make_bench_workload
from trlx_tpu.parallel import replicated
from trlx_tpu.trainer.common import (
    TrainState, make_optimizer, unfrozen_param_mask,
)


def main():
    cfg, tr, pipe, orch = make_bench_workload()
    orch.make_experience(cfg.method.num_rollouts, 0)  # fill the buffer once
    seed_counter = itertools.count(1)

    def set_unfrozen(k):
        """Swap the freezing boundary in place: mask, optimizer (+fresh
        opt state), and re-jitted train fns (fresh closures)."""
        cfg.model.num_layers_unfrozen = k
        tr.trainable_mask = unfrozen_param_mask(
            tr.state.params, k, tr._n_layers()
        )
        tr.tx = make_optimizer(cfg.train, cfg.train.total_steps,
                               tr.trainable_mask)
        opt_shapes = jax.eval_shape(tr.tx.init, tr.state.params)
        tr.opt_shardings = tr._shardings_for(opt_shapes)
        new_opt = jax.jit(tr.tx.init, out_shardings=tr.opt_shardings)(
            tr.state.params
        )
        tr.state = TrainState(
            params=tr.state.params, opt_state=new_opt, step=tr.state.step
        )
        tr.state_shardings = TrainState(
            params=tr.param_shardings, opt_state=tr.opt_shardings,
            step=replicated(tr.mesh),
        )
        tr._build_jitted_fns()

    def roundtrip_ms():
        z = jnp.zeros(())
        force(z)
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            force(z)
            ts.append((time.perf_counter() - t0) * 1000)
        return min(ts)

    def measure(n=4):
        ts = []
        for i in range(n + 2):  # first two absorb compile + relayout
            t0 = time.perf_counter()
            tr.train_on_buffer(seed=next(seed_counter))
            force(jax.tree_util.tree_leaves(tr.state.params)[0])
            ts.append((time.perf_counter() - t0) * 1000)
        return ts[2:]

    best = {"full": float("inf"), "frozen_top2": float("inf")}
    for rnd in range(2):
        order = (
            [(-1, "full"), (2, "frozen_top2")]
            if rnd % 2 == 0
            else [(2, "frozen_top2"), (-1, "full")]
        )
        for k, name in order:
            set_unfrozen(k)
            best[name] = min(best[name], min(measure()))

    rt = roundtrip_ms()
    full = best["full"] - rt
    frozen = best["frozen_top2"] - rt
    print(json.dumps({
        "metric": "train_phase_ms_32_updates_B16_T112_gpt2s",
        "full_ms": round(full, 1),
        "frozen_top2_ms": round(frozen, 1),
        "speedup": round(full / frozen, 3),
        "roundtrip_ms_subtracted": round(rt, 1),
        "device_kind": jax.devices()[0].device_kind,
    }))


if __name__ == "__main__":
    main()
